"""graftlint — the repo's first-party JAX-hazard + concurrency linter.

AST-based and repo-aware: rules consult a project-wide function index,
jit-reachability with interprocedural taint, a logging-function
closure, (round 15) the concurrency layer — thread entry-point
discovery, per-function execution contexts, lock inventories, guard
regions and a blocking-call closure — and (round 18) the
compile-surface dataflow layer — shape/dtype-determining parameters
of every jit root propagated up the call graph, with bounded/unbounded
origin classification of the values reaching them (see
:mod:`tools.analysis.astutil` / :mod:`tools.analysis.rules` /
:mod:`tools.analysis.concurrency` /
:mod:`tools.analysis.compilesurface`).  Run it as::

    python -m tools.analysis racon_tpu tests tools bench.py
    python -m tools.analysis --selftest        # fixture-based rule tests
    python -m tools.analysis --list            # rule inventory
    python -m tools.analysis --json PATH       # machine JSON on stdout
    python -m tools.analysis --json out.json PATH   # ...to a CI artifact
    python -m tools.analysis --changed-only PATH    # git-diff set + import
                                               # neighbors (CI gate mode)
    python -m tools.analysis --timings PATH    # per-rule seconds to stderr
    python -m tools.analysis --rules-md        # README rule table (gated
                                               # by --check-readme README.md)

Suppression: a finding is silenced by a pragma **with a reason** on the
finding line or the line above::

    except Exception:  # graftlint: disable=swallowed-exception (probe)

A pragma without a reason does not suppress (the finding is reported
with a note), so every escape documents its justification.  Exit code 0
means zero unsuppressed findings.

The runtime half of the tool lives in ``racon_tpu/sanitize.py``
(``RACON_TPU_SANITIZE=1``): SWAR int32 shadow execution, kernel-output
canaries, the jit-retrace phase budget, the pipeline queue watchdog,
and the lock-order witness over the project's named locks (cycle =
potential deadlock, reported with the stack of every edge at process
exit).
"""

from __future__ import annotations

import pathlib
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .astutil import Module, Project, load_module
from .rules import ALL_RULES, RULES_BY_NAME, Finding, Rule

_PRAGMA = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_\-,\s]+?)"
    r"(?:\s*\((?P<reason>[^)]*)\))?\s*$")

EXCLUDE_PARTS = {"__pycache__", "fixtures", ".git"}


def pragma_rules(line: str) -> Optional[Tuple[List[str], str]]:
    """(rule names, reason) of a pragma on ``line``, else None."""
    m = _PRAGMA.search(line)
    if not m:
        return None
    rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
    return rules, (m.group("reason") or "").strip()


def collect_files(paths: Sequence[str]) -> List[pathlib.Path]:
    files: List[pathlib.Path] = []
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not (set(f.parts) & EXCLUDE_PARTS):
                    files.append(f)
        elif p.suffix == ".py":
            files.append(p)
        else:
            raise FileNotFoundError(f"not a Python file or directory: {raw}")
    return files


def _rel(path: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(
            pathlib.Path.cwd().resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def load_project(paths: Sequence[str]) -> Project:
    return Project([load_module(f, _rel(f)) for f in collect_files(paths)])


def apply_pragmas(module: Module,
                  findings: Iterable[Finding]) -> Tuple[List[Finding],
                                                        List[Finding]]:
    """Split findings into (reported, suppressed) per the module's
    pragmas. Unknown rule names in pragmas and missing reasons become
    extra findings — the pragma escape polices itself."""
    reported: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        verdict = None
        for line_no in (f.line, f.line - 1):
            parsed = pragma_rules(module.line(line_no))
            if parsed is None:
                continue
            rules, reason = parsed
            if f.rule in rules:
                verdict = (line_no, reason)
                break
        if verdict is None:
            reported.append(f)
        elif not verdict[1]:
            f.message += " [pragma present but missing its (reason)]"
            reported.append(f)
        else:
            f.pragma = verdict[1]
            suppressed.append(f)
    return reported, suppressed


def check_pragma_hygiene(module: Module) -> List[Finding]:
    """Pragmas naming unknown rules are themselves findings (a typo'd
    pragma silently suppresses nothing — surface it)."""
    out: List[Finding] = []
    for i, line in enumerate(module.lines, 1):
        parsed = pragma_rules(line)
        if parsed is None:
            continue
        for rule in parsed[0]:
            if rule not in RULES_BY_NAME:
                out.append(Finding(
                    "pragma", module.rel, i,
                    f"pragma names unknown rule {rule!r} (known: "
                    f"{', '.join(sorted(RULES_BY_NAME))})"))
    return out


# ------------------------------------------------------- incremental mode

# a change to the analyzer itself or to a registry EVERY rule reads
# invalidates any incremental skip: fall back to the full run
_FULL_RUN_TRIGGERS = ("tools/analysis/", "racon_tpu/contracts.py",
                      "racon_tpu/flags.py")


def changed_rels() -> Optional[set]:
    """Repo-relative ``.py`` files changed vs HEAD (worktree diff +
    untracked), per git.  None = incremental mode unavailable (no git,
    or the analyzer/registries themselves changed) — callers fall back
    to the full run.  Paths come from git, so the caller must run from
    the repo root (CI does)."""
    import subprocess
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, timeout=30)
        extra = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    if diff.returncode != 0 or extra.returncode != 0:
        return None
    rels = {line.strip()
            for line in (diff.stdout + extra.stdout).splitlines()
            if line.strip().endswith(".py")}
    if any(r.startswith(_FULL_RUN_TRIGGERS) for r in rels):
        return None
    return rels


def expand_changed(project: Project, changed: set) -> set:
    """The changed set plus its import neighbors in BOTH directions:
    modules a changed module imports (its contracts may have moved)
    and modules importing a changed one (their use sites may have
    broken).  One hop — the project index the rules consult is still
    built over the WHOLE tree, so deeper effects (jit taint, lock
    closures) stay correct; the hop only widens which modules get
    re-checked."""
    prov = project.provenance()
    dotted_to_rel = {d: m.rel for d, m in prov._by_dotted.items()}
    imports_of = {}
    for m in project.modules:
        cands = set()
        for (mod, member) in prov.imports(m).values():
            cands.add(mod)
            if member:
                cands.add(f"{mod}.{member}")
        imports_of[m.rel] = cands
    changed_dotted = {d for d, r in dotted_to_rel.items() if r in changed}
    out = set(changed)
    for m in project.modules:
        if imports_of[m.rel] & changed_dotted:
            out.add(m.rel)
    for r in changed:
        for cand in imports_of.get(r, ()):
            if cand in dotted_to_rel:
                out.add(dotted_to_rel[cand])
    return out


def run(paths: Sequence[str],
        rules: Optional[Sequence[Rule]] = None,
        scoped: bool = True,
        only: Optional[set] = None,
        timings: Optional[Dict[str, float]] = None,
        ) -> Tuple[List[Finding], List[Finding]]:
    """Lint ``paths``; returns (reported, suppressed). ``scoped=False``
    disables per-rule path scoping (the selftest fixtures live outside
    the rules' production scopes).  ``only`` restricts which modules'
    findings are computed (the full project is still parsed and
    indexed — incremental mode narrows checking, never the rules'
    view).  ``timings`` accumulates per-rule wall seconds in place."""
    import time
    project = load_project(paths)
    if only is not None:
        only = expand_changed(project, only)
    rules = list(rules if rules is not None else ALL_RULES)
    reported: List[Finding] = []
    suppressed: List[Finding] = []
    for module in project.modules:
        if only is not None and module.rel not in only:
            continue
        found: List[Finding] = []
        for rule in rules:
            if scoped and not rule.applies(module.rel):
                continue
            if timings is None:
                found.extend(rule.check(project, module))
            else:
                t0 = time.perf_counter()
                found.extend(rule.check(project, module))
                timings[rule.name] = (timings.get(rule.name, 0.0)
                                      + time.perf_counter() - t0)
        rep, sup = apply_pragmas(module, found)
        reported.extend(rep)
        suppressed.extend(sup)
        reported.extend(check_pragma_hygiene(module))
    reported.sort(key=lambda f: (f.rel, f.line, f.rule))
    return reported, suppressed


# ------------------------------------------------------- README generation

_TABLE_NOTE = ("<!-- generated by `python -m tools.analysis --rules-md` "
               "from tools/analysis — do not edit by hand -->")


def rules_md() -> str:
    """The README "Static analysis" rule table, generated from the live
    rule registry (one row per rule, registration order) — the same
    generate-and-gate mechanism as the flags table."""
    lines = [_TABLE_NOTE, "",
             "| rule | catches |",
             "| --- | --- |"]
    for rule in ALL_RULES:
        lines.append(f"| `{rule.name}` | {rule.blurb} |")
    return "\n".join(lines) + "\n"


def check_readme(path: str) -> bool:
    """True when ``path`` contains the current generated rule table
    verbatim (the lint shard runs this so the README cannot drift)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return rules_md() in fh.read()
    except OSError:
        return False


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--list" in argv:
        for rule in ALL_RULES:
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.name}: {doc}")
        return 0
    if "--selftest" in argv:
        from .selftest import run_selftest
        return run_selftest()
    if "--rules-md" in argv:
        print(rules_md(), end="")
        return 0
    if "--check-readme" in argv:
        i = argv.index("--check-readme")
        target = (argv[i + 1] if i + 1 < len(argv) else "README.md")
        if check_readme(target):
            return 0
        print("README static-analysis rule table is stale — regenerate "
              "with `python -m tools.analysis --rules-md` and paste the "
              "output", file=sys.stderr)
        return 1
    quiet = "--quiet" in argv
    changed_only = "--changed-only" in argv
    want_timings = "--timings" in argv
    as_json = "--json" in argv
    json_path: Optional[str] = None
    if as_json:
        # `--json FILE.json` writes the machine-readable record to a CI
        # artifact file (diffable across runs) while the human findings
        # keep printing; bare `--json` prints the JSON to stdout.  The
        # artifact slot is STRICTLY `.json`-suffixed: any other token
        # stays a scan path, so a mistyped tree fails the run loudly
        # instead of being silently consumed as the output file.
        i = argv.index("--json")
        if i + 1 < len(argv) and argv[i + 1].endswith(".json"):
            json_path = argv.pop(i + 1)
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print("usage: python -m tools.analysis [--selftest|--list|"
              "--rules-md|--check-readme [README]|--changed-only|"
              "--timings|--json [FILE.json]] PATH [PATH...]",
              file=sys.stderr)
        return 2
    only: Optional[set] = None
    if changed_only:
        only = changed_rels()
        if only is None:
            print("graftlint: --changed-only unavailable (no git, or "
                  "the analyzer/registries changed) — full run",
                  file=sys.stderr)
        elif not quiet:
            print(f"graftlint: --changed-only over {len(only)} changed "
                  f"file(s) + import neighbors", file=sys.stderr)
    timings: Optional[Dict[str, float]] = {} if want_timings else None
    try:
        reported, suppressed = run(paths, only=only, timings=timings)
    except (FileNotFoundError, SyntaxError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    if timings is not None:
        for name, secs in sorted(timings.items(),
                                 key=lambda kv: -kv[1]):
            print(f"graftlint timing: {name} {secs:.2f}s",
                  file=sys.stderr)
    if as_json:
        # machine-readable output for CI annotation/aggregation: every
        # finding (reported AND pragma-suppressed, distinguished by the
        # pragma field) as one JSON object
        import json
        blob = json.dumps({
            "findings": [f.as_dict() for f in reported],
            "suppressed": [f.as_dict() for f in suppressed],
        }, indent=1)
        if json_path is not None:
            with open(json_path, "w", encoding="utf-8") as fh:
                fh.write(blob + "\n")
            for f in reported:
                print(f)
        else:
            print(blob)
    else:
        for f in reported:
            print(f)
    if not quiet:
        print(f"graftlint: {len(reported)} finding(s), "
              f"{len(suppressed)} suppressed by pragma", file=sys.stderr)
    return 1 if reported else 0
