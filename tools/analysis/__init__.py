"""graftlint — the repo's first-party JAX-hazard + concurrency linter.

AST-based and repo-aware: rules consult a project-wide function index,
jit-reachability with interprocedural taint, a logging-function
closure, (round 15) the concurrency layer — thread entry-point
discovery, per-function execution contexts, lock inventories, guard
regions and a blocking-call closure — and (round 18) the
compile-surface dataflow layer — shape/dtype-determining parameters
of every jit root propagated up the call graph, with bounded/unbounded
origin classification of the values reaching them (see
:mod:`tools.analysis.astutil` / :mod:`tools.analysis.rules` /
:mod:`tools.analysis.concurrency` /
:mod:`tools.analysis.compilesurface`).  Run it as::

    python -m tools.analysis racon_tpu tests tools bench.py
    python -m tools.analysis --selftest        # fixture-based rule tests
    python -m tools.analysis --list            # rule inventory
    python -m tools.analysis --json PATH       # machine JSON on stdout
    python -m tools.analysis --json out.json PATH   # ...to a CI artifact

Suppression: a finding is silenced by a pragma **with a reason** on the
finding line or the line above::

    except Exception:  # graftlint: disable=swallowed-exception (probe)

A pragma without a reason does not suppress (the finding is reported
with a note), so every escape documents its justification.  Exit code 0
means zero unsuppressed findings.

The runtime half of the tool lives in ``racon_tpu/sanitize.py``
(``RACON_TPU_SANITIZE=1``): SWAR int32 shadow execution, kernel-output
canaries, the jit-retrace phase budget, the pipeline queue watchdog,
and the lock-order witness over the project's named locks (cycle =
potential deadlock, reported with the stack of every edge at process
exit).
"""

from __future__ import annotations

import pathlib
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .astutil import Module, Project, load_module
from .rules import ALL_RULES, RULES_BY_NAME, Finding, Rule

_PRAGMA = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_\-,\s]+?)"
    r"(?:\s*\((?P<reason>[^)]*)\))?\s*$")

EXCLUDE_PARTS = {"__pycache__", "fixtures", ".git"}


def pragma_rules(line: str) -> Optional[Tuple[List[str], str]]:
    """(rule names, reason) of a pragma on ``line``, else None."""
    m = _PRAGMA.search(line)
    if not m:
        return None
    rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
    return rules, (m.group("reason") or "").strip()


def collect_files(paths: Sequence[str]) -> List[pathlib.Path]:
    files: List[pathlib.Path] = []
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not (set(f.parts) & EXCLUDE_PARTS):
                    files.append(f)
        elif p.suffix == ".py":
            files.append(p)
        else:
            raise FileNotFoundError(f"not a Python file or directory: {raw}")
    return files


def _rel(path: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(
            pathlib.Path.cwd().resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def load_project(paths: Sequence[str]) -> Project:
    return Project([load_module(f, _rel(f)) for f in collect_files(paths)])


def apply_pragmas(module: Module,
                  findings: Iterable[Finding]) -> Tuple[List[Finding],
                                                        List[Finding]]:
    """Split findings into (reported, suppressed) per the module's
    pragmas. Unknown rule names in pragmas and missing reasons become
    extra findings — the pragma escape polices itself."""
    reported: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        verdict = None
        for line_no in (f.line, f.line - 1):
            parsed = pragma_rules(module.line(line_no))
            if parsed is None:
                continue
            rules, reason = parsed
            if f.rule in rules:
                verdict = (line_no, reason)
                break
        if verdict is None:
            reported.append(f)
        elif not verdict[1]:
            f.message += " [pragma present but missing its (reason)]"
            reported.append(f)
        else:
            f.pragma = verdict[1]
            suppressed.append(f)
    return reported, suppressed


def check_pragma_hygiene(module: Module) -> List[Finding]:
    """Pragmas naming unknown rules are themselves findings (a typo'd
    pragma silently suppresses nothing — surface it)."""
    out: List[Finding] = []
    for i, line in enumerate(module.lines, 1):
        parsed = pragma_rules(line)
        if parsed is None:
            continue
        for rule in parsed[0]:
            if rule not in RULES_BY_NAME:
                out.append(Finding(
                    "pragma", module.rel, i,
                    f"pragma names unknown rule {rule!r} (known: "
                    f"{', '.join(sorted(RULES_BY_NAME))})"))
    return out


def run(paths: Sequence[str],
        rules: Optional[Sequence[Rule]] = None,
        scoped: bool = True) -> Tuple[List[Finding], List[Finding]]:
    """Lint ``paths``; returns (reported, suppressed). ``scoped=False``
    disables per-rule path scoping (the selftest fixtures live outside
    the rules' production scopes)."""
    project = load_project(paths)
    rules = list(rules if rules is not None else ALL_RULES)
    reported: List[Finding] = []
    suppressed: List[Finding] = []
    for module in project.modules:
        found: List[Finding] = []
        for rule in rules:
            if scoped and not rule.applies(module.rel):
                continue
            found.extend(rule.check(project, module))
        rep, sup = apply_pragmas(module, found)
        reported.extend(rep)
        suppressed.extend(sup)
        reported.extend(check_pragma_hygiene(module))
    reported.sort(key=lambda f: (f.rel, f.line, f.rule))
    return reported, suppressed


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--list" in argv:
        for rule in ALL_RULES:
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.name}: {doc}")
        return 0
    if "--selftest" in argv:
        from .selftest import run_selftest
        return run_selftest()
    quiet = "--quiet" in argv
    as_json = "--json" in argv
    json_path: Optional[str] = None
    if as_json:
        # `--json FILE.json` writes the machine-readable record to a CI
        # artifact file (diffable across runs) while the human findings
        # keep printing; bare `--json` prints the JSON to stdout.  The
        # artifact slot is STRICTLY `.json`-suffixed: any other token
        # stays a scan path, so a mistyped tree fails the run loudly
        # instead of being silently consumed as the output file.
        i = argv.index("--json")
        if i + 1 < len(argv) and argv[i + 1].endswith(".json"):
            json_path = argv.pop(i + 1)
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print("usage: python -m tools.analysis [--selftest|--list|"
              "--json [FILE.json]] PATH [PATH...]", file=sys.stderr)
        return 2
    try:
        reported, suppressed = run(paths)
    except (FileNotFoundError, SyntaxError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    if as_json:
        # machine-readable output for CI annotation/aggregation: every
        # finding (reported AND pragma-suppressed, distinguished by the
        # pragma field) as one JSON object
        import json
        blob = json.dumps({
            "findings": [f.as_dict() for f in reported],
            "suppressed": [f.as_dict() for f in suppressed],
        }, indent=1)
        if json_path is not None:
            with open(json_path, "w", encoding="utf-8") as fh:
                fh.write(blob + "\n")
            for f in reported:
                print(f)
        else:
            print(blob)
    else:
        for f in reported:
            print(f)
    if not quiet:
        print(f"graftlint: {len(reported)} finding(s), "
              f"{len(suppressed)} suppressed by pragma", file=sys.stderr)
    return 1 if reported else 0
