"""``python -m tools.analysis`` entry point."""

import sys

from . import main

sys.exit(main())
