"""AST plumbing shared by the graftlint rules.

Everything here is *static*: source files are parsed, never imported, so
the linter runs without jax (and on broken code). The central products:

- :class:`Project` — the parsed module set plus repo-aware indexes: a
  name-keyed function index, the set of jit-traced roots (``@jax.jit``
  and friends, plus Pallas kernels by their positional ``*_ref`` /
  keyword-only-static convention), tracer-reachability over the repo
  call graph, and an interprocedural **taint** of traced values that the
  ``tracer-leak`` rule consumes.
- the **concurrency layer** (round 15): thread entry-point discovery
  (every ``threading.Thread(target=...)``, targets resolved),
  **execution contexts** per function (which thread roots — and/or the
  main path — can run it, propagated over an unambiguous call graph),
  per-class/per-module **lock inventories** (``self._lock =
  threading.Lock()``, ``Condition(self._lock)`` aliases), lexical
  **guard regions** (:func:`guarded_nodes`), and a **blocking-call
  closure** (functions that transitively sleep/fsync/send/queue-block).
  The concurrency/durability rule pack in
  :mod:`tools.analysis.concurrency` consumes all of these.
- :func:`dotted` — best-effort dotted name of an expression
  (``jax.jit``, ``os.environ.get``), the workhorse of call matching.

The taint model: a name is *traced* if it is a non-static parameter of a
jit-traced function, or derives from one through assignments, or is the
result of a ``jnp.`` / ``lax.`` / ``jax.`` call.  Shape/dtype attribute
reads and ``isinstance``/``len``/``type`` calls launder taint (their
results are static under tracing).  Taint flows across calls resolved in
the repo (positional and keyword args mapped onto the callee signature),
and into nested functions (tracing callbacks for ``scan``/``vmap``)
whose own parameters are traced by construction.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

JIT_NAMES = {"jax.jit", "jit"}
PARTIAL_NAMES = {"functools.partial", "partial"}
# attribute reads that are static under tracing (reading them off a
# tracer yields a concrete Python value)
STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}
# calls that are static under tracing even on traced operands
STATIC_CALLS = {"isinstance", "len", "type", "hasattr", "callable", "id",
                "repr", "str", "format"}
# call prefixes that produce traced values
TRACED_PREFIXES = ("jnp.", "lax.", "jax.", "pl.", "pltpu.")

# ------------------------------------------------- concurrency vocabulary
THREAD_CTORS = {"threading.Thread", "Thread"}
LOCK_CTORS = {"threading.Lock", "Lock", "threading.RLock", "RLock",
              "named_lock", "sanitize.named_lock"}
CONDITION_CTORS = {"threading.Condition", "Condition"}
QUEUE_CTORS = {"Queue", "queue.Queue", "SimpleQueue", "queue.SimpleQueue"}
MAIN_CONTEXT = "main"
# attribute-call names that are overwhelmingly stdlib-object protocol
# (Thread.start/join, Event.set/wait, dict/list mutation, file I/O):
# resolving them to same-named repo functions through a non-self
# receiver would wire bogus call-graph edges
GENERIC_METHODS = {"start", "join", "set", "clear", "is_set", "wait",
                   "acquire", "release", "get", "put", "get_nowait",
                   "put_nowait", "append", "add", "pop", "remove",
                   "update", "items", "keys", "values", "close",
                   "flush", "write", "read", "readline", "send",
                   "sendall", "recv", "accept", "connect"}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


@dataclass
class Module:
    path: pathlib.Path
    rel: str                      # posix path relative to the lint root
    tree: ast.AST
    lines: List[str]

    def line(self, n: int) -> str:
        return self.lines[n - 1] if 0 < n <= len(self.lines) else ""


def load_module(path: pathlib.Path, rel: str) -> Module:
    src = path.read_text(encoding="utf-8")
    return Module(path, rel, ast.parse(src, filename=str(path)),
                  src.splitlines())


@dataclass
class FuncInfo:
    module: Module
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    qualname: str
    parent: Optional["FuncInfo"]  # lexically enclosing function
    class_name: Optional[str]
    is_jit_root: bool = False
    is_kernel_root: bool = False
    static_argnames: Set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        return self.node.name

    def params(self, *, drop_self: bool = False) -> List[str]:
        a = self.node.args
        names = ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args])
        if drop_self and names and names[0] in ("self", "cls"):
            names = names[1:]
        return names

    def kwonly_params(self) -> List[str]:
        return [p.arg for p in self.node.args.kwonlyargs]

    def all_params(self) -> List[str]:
        extra = []
        if self.node.args.vararg:
            extra.append(self.node.args.vararg.arg)
        if self.node.args.kwarg:
            extra.append(self.node.args.kwarg.arg)
        return self.params() + self.kwonly_params() + extra


def _jit_decoration(dec: ast.AST) -> Optional[Set[str]]:
    """If ``dec`` marks a jit root, return its static_argnames set."""
    if dotted(dec) in JIT_NAMES:
        return set()
    if isinstance(dec, ast.Call):
        fn = dotted(dec.func)
        if fn in JIT_NAMES:
            return _static_argnames(dec)
        if fn in PARTIAL_NAMES and dec.args \
                and dotted(dec.args[0]) in JIT_NAMES:
            return _static_argnames(dec)
    return None


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
    return set()


def _is_pallas_kernel(node: ast.AST) -> bool:
    """Pallas kernels follow the repo convention: positional ``*_ref``
    parameters (Refs, traced) plus keyword-only static geometry."""
    names = [p.arg for p in node.args.posonlyargs + node.args.args]
    return any(n.endswith("_ref") for n in names)


class Project:
    """Parsed modules plus lazily-built repo-wide indexes."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.functions: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.func_of_node: Dict[ast.AST, FuncInfo] = {}
        self._index()
        self._taint: Optional[Dict[int, Set[str]]] = None
        self._reachable: Optional[Set[int]] = None
        self._logging: Optional[Set[int]] = None
        self._provenance: Optional["StringProvenance"] = None

    def provenance(self) -> "StringProvenance":
        """The cached cross-module string-constant resolver."""
        if self._provenance is None:
            self._provenance = StringProvenance(self)
        return self._provenance

    # ------------------------------------------------------------ indexing

    def _index(self) -> None:
        for mod in self.modules:
            self._index_scope(mod, mod.tree, None, None, prefix="")

    def _index_scope(self, mod, node, parent_fn, class_name, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                statics: Set[str] = set()
                is_jit = False
                for dec in child.decorator_list:
                    s = _jit_decoration(dec)
                    if s is not None:
                        is_jit, statics = True, s
                is_kernel = (not is_jit and child.name.endswith("_kernel")
                             and _is_pallas_kernel(child))
                fi = FuncInfo(mod, child, qual, parent_fn, class_name,
                              is_jit, is_kernel, statics)
                self.functions.append(fi)
                self.by_name.setdefault(child.name, []).append(fi)
                self.func_of_node[child] = fi
                self._index_scope(mod, child, fi, class_name,
                                  prefix=qual + ".")
            elif isinstance(child, ast.ClassDef):
                self._index_scope(mod, child, parent_fn, child.name,
                                  prefix=f"{prefix}{child.name}.")
            else:
                self._index_scope(mod, child, parent_fn, class_name, prefix)

    def resolve(self, call: ast.Call) -> List[FuncInfo]:
        """Candidate repo definitions for a call, by terminal name."""
        name = last_segment(dotted(call.func))
        return self.by_name.get(name, []) if name else []

    def enclosing(self, fi: FuncInfo) -> List[FuncInfo]:
        chain = []
        cur = fi.parent
        while cur is not None:
            chain.append(cur)
            cur = cur.parent
        return chain

    # --------------------------------------------------- jit roots + taint

    def roots(self) -> List[Tuple[FuncInfo, Set[str]]]:
        """(function, initially traced parameter names) for every
        jit-traced entry point: jit-decorated defs (non-static params)
        and Pallas kernels (positional Ref params)."""
        out = []
        for fi in self.functions:
            if fi.is_jit_root:
                traced = {p for p in fi.all_params()
                          if p not in fi.static_argnames
                          and p not in ("self", "cls")}
                out.append((fi, traced))
            elif fi.is_kernel_root:
                out.append((fi, set(fi.params())))
        return out

    def taints(self) -> Dict[int, Set[str]]:
        """Fixpoint map ``id(FuncInfo) -> traced local names`` over every
        tracer-reachable function (the side product is
        :meth:`reachable`)."""
        if self._taint is not None:
            return self._taint
        param_taint: Dict[int, Set[str]] = {}
        info: Dict[int, FuncInfo] = {}
        work: List[FuncInfo] = []

        def seed(fi: FuncInfo, names: Set[str]) -> None:
            key = id(fi)
            info[key] = fi
            prev = param_taint.get(key)
            if prev is None or not names <= prev:
                param_taint[key] = (prev or set()) | names
                if fi not in work:
                    work.append(fi)

        for fi, traced in self.roots():
            seed(fi, traced)

        final: Dict[int, Set[str]] = {}
        guard = 0
        while work and guard < 10000:
            guard += 1
            fi = work.pop(0)
            names = self._intra_taint(fi, param_taint[id(fi)])
            final[id(fi)] = names
            # propagate into repo callees through mapped arguments
            for call in iter_own_calls(fi.node):
                for callee in self.resolve(call):
                    mapped = map_call_args(call, callee)
                    if mapped is None:
                        continue
                    tainted_params = {
                        p for p, expr in mapped.items()
                        if expr is not None
                        and self.expr_tainted(expr, names)}
                    seed(callee, tainted_params)
            # directly nested defs: closure names carry the enclosing
            # taint; parameters are tainted by how the function is used —
            # direct calls map argument taint (handled above via
            # resolve()), while *escaping* uses (passed to scan/vmap/
            # pallas_call, stored) trace every parameter except ones a
            # functools.partial binds to untainted values
            for child in ast.walk(fi.node):
                sub = self.func_of_node.get(child)
                if sub is not None and sub.parent is fi:
                    seed(sub, names & free_names(sub.node))
                    esc = self._escape_taint(fi, sub, names)
                    if esc:
                        seed(sub, esc)
        self._taint = final
        self._reachable = set(final)
        return final

    def reachable(self) -> Set[int]:
        self.taints()
        return self._reachable or set()

    def _escape_taint(self, fi: FuncInfo, sub: FuncInfo,
                      names: Set[str]) -> Set[str]:
        """Traced parameters of nested ``sub`` implied by how ``fi``
        *uses* it beyond direct calls. A ``functools.partial(sub, ...)``
        binds the mapped params to the taint of the bound expressions;
        any other escaping reference (an argument to scan/vmap/
        pallas_call, an assignment) traces every parameter."""
        out: Set[str] = set()
        covered: Set[int] = set()
        params = sub.params()
        for call in iter_own_calls(fi.node):
            if isinstance(call.func, ast.Name) \
                    and call.func.id == sub.name:
                covered.add(id(call.func))  # direct call: mapped above
            elif dotted(call.func) in PARTIAL_NAMES and call.args \
                    and isinstance(call.args[0], ast.Name) \
                    and call.args[0].id == sub.name:
                covered.add(id(call.args[0]))
                bound: Dict[str, ast.AST] = {}
                for p, a in zip(params, call.args[1:]):
                    bound[p] = a
                for kw in call.keywords:
                    if kw.arg is not None:
                        bound[kw.arg] = kw.value
                for p in sub.all_params():
                    expr = bound.get(p)
                    if expr is None or self.expr_tainted(expr, names):
                        out.add(p)
        for node in iter_own_nodes(fi.node):
            if isinstance(node, ast.Name) and node.id == sub.name \
                    and id(node) not in covered:
                return out | set(sub.all_params())  # raw escape
        return out

    def _intra_taint(self, fi: FuncInfo, seeded: Set[str]) -> Set[str]:
        """Forward taint propagation over the function's own statements
        (nested defs excluded), iterated to a small fixpoint so loops
        converge."""
        tainted = set(seeded)
        for _ in range(10):
            grew = False
            for node in iter_own_nodes(fi.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    targets, value = [node.target], node.iter
                elif isinstance(node, ast.comprehension):
                    targets, value = [node.target], node.iter
                if value is None or not self.expr_tainted(value, tainted):
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) \
                                and n.id not in tainted:
                            tainted.add(n.id)
                            grew = True
            if not grew:
                break
        return tainted

    def expr_tainted(self, node: ast.AST, tainted: Set[str]) -> bool:
        """Does the expression's value derive from a traced value?"""
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value, tainted)
        if isinstance(node, ast.Call):
            fn = dotted(node.func)
            if fn in STATIC_CALLS:
                return False
            if fn and (fn.startswith(TRACED_PREFIXES) or fn in
                       ("vmap", "scan", "cond", "while_loop")):
                return True
            args = list(node.args) + [kw.value for kw in node.keywords]
            if isinstance(node.func, ast.Attribute) \
                    and self.expr_tainted(node.func.value, tainted):
                return True  # method call on a traced value
            return any(self.expr_tainted(a, tainted) for a in args)
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value, tainted)
        return any(self.expr_tainted(c, tainted)
                   for c in ast.iter_child_nodes(node)
                   if isinstance(c, ast.expr))

    # ------------------------------------------------------ logging closure

    DIRECT_LOG_NAMES = {"warn", "warning", "log_swallowed", "error",
                        "exception", "critical"}

    def _call_logs_directly(self, call: ast.Call) -> bool:
        fn = dotted(call.func)
        if fn == "warnings.warn":
            return True
        seg = last_segment(fn)
        # strip private-alias underscores: `_log_swallowed` is the same
        # sanctioned sink as `log_swallowed`
        if seg and seg.lstrip("_") in self.DIRECT_LOG_NAMES:
            return True
        # print(..., file=<not stdout>) is the stderr logging idiom
        if fn == "print":
            return any(kw.arg == "file" for kw in call.keywords)
        return False

    def logging_functions(self) -> Set[int]:
        """ids of repo functions that (transitively) emit a log line —
        the repo-aware half of the swallowed-exception rule."""
        if self._logging is not None:
            return self._logging
        logs: Set[int] = set()
        for fi in self.functions:
            for call in iter_own_calls(fi.node):
                if self._call_logs_directly(call):
                    logs.add(id(fi))
                    break
        changed = True
        guard = 0
        while changed and guard < 100:
            guard += 1
            changed = False
            for fi in self.functions:
                if id(fi) in logs:
                    continue
                for call in iter_own_calls(fi.node):
                    if any(id(c) in logs for c in self.resolve(call)):
                        logs.add(id(fi))
                        changed = True
                        break
        self._logging = logs
        return logs

    def call_is_logging(self, call: ast.Call) -> bool:
        if self._call_logs_directly(call):
            return True
        return any(id(c) in self.logging_functions()
                   for c in self.resolve(call))

    # ------------------------------------------------- concurrency layer

    def resolve_unique(self, call: ast.Call,
                       caller: Optional[FuncInfo]) -> Optional[FuncInfo]:
        """The single repo definition a call can mean, or None.

        Unlike :meth:`resolve` (every same-named candidate — right for
        may-analyses like the logging closure), context propagation
        must not smear thread-ness through common names (``run`` is
        defined by half the engine classes): a ``self.m(...)`` call
        binds to the enclosing class's own method; a bare name binds to
        a lexically nested def first; anything else resolves only when
        exactly one definition carries the name."""
        name = last_segment(dotted(call.func))
        if name is None:
            return None
        if isinstance(call.func, ast.Attribute):
            on_self = (isinstance(call.func.value, ast.Name)
                       and call.func.value.id in ("self", "cls"))
            if on_self and caller is not None and caller.class_name:
                own = [c for c in self.by_name.get(name, [])
                       if c.class_name == caller.class_name
                       and c.module is caller.module]
                if own:
                    return own[0]
            if not on_self and name in GENERIC_METHODS:
                return None
        if caller is not None and isinstance(call.func, ast.Name):
            for scope in [caller] + self.enclosing(caller):
                for c in self.by_name.get(name, []):
                    if c.parent is scope:
                        return c
        cands = self.by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def _resolve_callable_ref(self, expr: ast.AST,
                              owner: Optional[FuncInfo],
                              module: Module) -> List[FuncInfo]:
        """Repo definitions a callable *reference* (a ``target=`` value)
        can mean: ``self.m`` binds in the owner's class, a bare name
        binds to a nested def first, then uniquely by name."""
        name = last_segment(dotted(expr))
        if name is None:
            return []
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in ("self", "cls"):
            cls = owner.class_name if owner else None
            own = [c for c in self.by_name.get(name, [])
                   if c.class_name == cls and c.module is module]
            if own:
                return own
        if isinstance(expr, ast.Name) and owner is not None:
            for scope in [owner] + self.enclosing(owner):
                for c in self.by_name.get(name, []):
                    if c.parent is scope:
                        return [c]
        cands = self.by_name.get(name, [])
        return cands if len(cands) == 1 else []

    def thread_spawns(self) -> List["ThreadSpawn"]:
        """Every ``threading.Thread(target=...)`` construction in the
        project, with its resolved target functions — the thread
        entry-point discovery the concurrency rules build on."""
        if getattr(self, "_spawns", None) is not None:
            return self._spawns
        spawns: List[ThreadSpawn] = []
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and dotted(node.func) in THREAD_CTORS):
                    continue
                owner = self._enclosing_function(mod, node)
                target = None
                daemon = False
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                    elif kw.arg == "daemon" \
                            and isinstance(kw.value, ast.Constant):
                        daemon = bool(kw.value.value)
                targets = ([] if target is None else
                           self._resolve_callable_ref(target, owner, mod))
                spawns.append(ThreadSpawn(mod, node, owner, targets,
                                          daemon))
        self._spawns = spawns
        return spawns

    def _enclosing_function(self, module: Module,
                            node: ast.AST) -> Optional[FuncInfo]:
        """The innermost function whose body contains ``node`` (by line
        span — cheap and adequate for spawn-site attribution)."""
        best: Optional[FuncInfo] = None
        lineno = getattr(node, "lineno", None)
        if lineno is None:
            return None
        for fi in self.functions:
            if fi.module is not module:
                continue
            end = getattr(fi.node, "end_lineno", fi.node.lineno)
            if fi.node.lineno <= lineno <= end:
                if best is None or fi.node.lineno > best.node.lineno:
                    best = fi
        return best

    def thread_roots(self) -> List[FuncInfo]:
        """Functions that run as thread entry points (deduped)."""
        seen: Set[int] = set()
        out: List[FuncInfo] = []
        for spawn in self.thread_spawns():
            for fi in spawn.targets:
                if id(fi) not in seen:
                    seen.add(id(fi))
                    out.append(fi)
        return out

    def exec_contexts(self) -> Dict[int, Set[str]]:
        """``id(FuncInfo) -> execution context labels``: the set of
        thread roots (``thread:<qualname>``) — and/or :data:`MAIN_CONTEXT`
        — whose dynamic extent can reach the function.  Propagated to a
        fixpoint over the *unambiguous* call graph
        (:meth:`resolve_unique`): a function with no repo caller and no
        spawn site is a main entry (CLI mains, public API, tests)."""
        if getattr(self, "_contexts", None) is not None:
            return self._contexts
        roots = {id(fi): f"thread:{fi.qualname}"
                 for fi in self.thread_roots()}
        edges: Dict[int, Set[int]] = {}
        called: Set[int] = set()
        for fi in self.functions:
            for call in iter_own_calls(fi.node):
                tgt = self.resolve_unique(call, fi)
                if tgt is not None:
                    edges.setdefault(id(fi), set()).add(id(tgt))
                    called.add(id(tgt))
        ctx: Dict[int, Set[str]] = {}
        for fi in self.functions:
            k = id(fi)
            ctx[k] = set()
            if k in roots:
                ctx[k].add(roots[k])
            if k not in called and k not in roots:
                ctx[k].add(MAIN_CONTEXT)
        # module-level calls run on the importing (main) thread
        for mod in self.modules:
            for node in module_level_calls(mod.tree):
                tgt = self.resolve_unique(node, None)
                if tgt is not None:
                    ctx[id(tgt)].add(MAIN_CONTEXT)
        changed = True
        guard = 0
        while changed and guard < 1000:
            guard += 1
            changed = False
            for src, dsts in edges.items():
                for dst in dsts:
                    if not ctx[src] <= ctx[dst]:
                        ctx[dst] |= ctx[src]
                        changed = True
        self._contexts = ctx
        return ctx

    def lock_inventory(self, module: Module) -> "LockInventory":
        """The module's named locks: per-class ``self.X`` lock
        attributes (``Condition(self.Y)`` aliases to ``Y``) and
        module-level lock globals — what :func:`guarded_nodes` treats
        as guards."""
        cache = getattr(self, "_lock_inv", None)
        if cache is None:
            cache = self._lock_inv = {}
        inv = cache.get(id(module))
        if inv is not None:
            return inv
        by_class: Dict[str, Dict[str, str]] = {}
        module_locks: Set[str] = set()
        for fi in self.functions:
            if fi.module is not module or not fi.class_name:
                continue
            attrs = by_class.setdefault(fi.class_name, {})
            for node in iter_own_nodes(fi.node):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                fn = dotted(node.value.func)
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    if fn in LOCK_CTORS:
                        attrs[t.attr] = t.attr
                    elif fn in CONDITION_CTORS:
                        # Condition(self.Y) holds Y; a bare Condition()
                        # owns its internal lock — canonical = itself
                        args = node.value.args
                        if args and isinstance(args[0], ast.Attribute) \
                                and isinstance(args[0].value, ast.Name) \
                                and args[0].value.id == "self":
                            attrs[t.attr] = args[0].attr
                        else:
                            attrs[t.attr] = t.attr
        for node in ast.iter_child_nodes(module.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and dotted(node.value.func) in LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        module_locks.add(t.id)
        inv = LockInventory(by_class, module_locks)
        cache[id(module)] = inv
        return inv

    # ------------------------------------------------- blocking closure

    def _call_blocks_directly(self, call: ast.Call,
                              queue_names: Set[str]) -> Optional[str]:
        """If this call can block the calling thread (sleep, fsync,
        socket I/O, subprocess, device sync, bounded-queue get/put),
        name the offending operation; else None.  ``Condition.wait``
        releases its lock and is exempt (receivers named ``*cond*``)."""
        fn = dotted(call.func)
        if fn in ("time.sleep",) or fn == "sleep":
            return "time.sleep"
        if fn and fn.startswith("subprocess."):
            return fn
        if fn in ("os.fsync", "jax.block_until_ready"):
            return fn
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            recv = dotted(call.func.value) or ""
            if attr in ("sendall", "recv", "recvfrom", "accept",
                        "connect", "fsync", "block_until_ready"):
                return f".{attr}"
            if attr == "wait" and "cond" not in recv.lower():
                return ".wait"
            if attr in ("get", "put"):
                seg = last_segment(recv) or ""
                if "queue" in seg.lower() or seg in queue_names:
                    return f"{seg}.{attr}"
        return None

    def _queue_names(self, fi: FuncInfo) -> Set[str]:
        """Local names bound to ``Queue(...)`` in ``fi`` or a lexically
        enclosing function (the polisher's ``ranges`` pattern)."""
        names: Set[str] = set()
        for f in [fi] + self.enclosing(fi):
            for node in iter_own_nodes(f.node):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and dotted(node.value.func) in QUEUE_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
        return names

    def blocking_functions(self) -> Set[int]:
        """ids of repo functions that (transitively, over the
        unambiguous call graph) can block: the interprocedural half of
        the ``blocking-under-lock`` rule — ``_save`` blocks because
        ``save_manifest -> durable_write -> atomic_write`` fsyncs."""
        if getattr(self, "_blocking", None) is not None:
            return self._blocking
        blocks: Set[int] = set()
        for fi in self.functions:
            qnames = self._queue_names(fi)
            for call in iter_own_calls(fi.node):
                if self._call_blocks_directly(call, qnames):
                    blocks.add(id(fi))
                    break
        changed = True
        guard = 0
        while changed and guard < 100:
            guard += 1
            changed = False
            for fi in self.functions:
                if id(fi) in blocks:
                    continue
                for call in iter_own_calls(fi.node):
                    tgt = self.resolve_unique(call, fi)
                    if tgt is not None and id(tgt) in blocks:
                        blocks.add(id(fi))
                        changed = True
                        break
        self._blocking = blocks
        return blocks

    def call_blocks(self, call: ast.Call,
                    caller: FuncInfo) -> Optional[str]:
        """Why a call (directly or via a repo callee) can block, or
        None."""
        why = self._call_blocks_directly(call, self._queue_names(caller))
        if why is not None:
            return why
        tgt = self.resolve_unique(call, caller)
        if tgt is not None and id(tgt) in self.blocking_functions():
            return f"{tgt.qualname}() (transitively blocking)"
        return None


@dataclass
class ThreadSpawn:
    """One ``threading.Thread(target=...)`` construction site."""

    module: Module
    call: ast.Call
    spawner: Optional[FuncInfo]     # None: module-level spawn
    targets: List[FuncInfo]         # resolved entry points (may be [])
    daemon: bool


@dataclass
class LockInventory:
    """One module's named locks (see :meth:`Project.lock_inventory`)."""

    by_class: Dict[str, Dict[str, str]]   # class -> {attr: canonical}
    module_locks: Set[str]                # module-global lock names

    def class_locks(self, class_name: Optional[str]) -> Dict[str, str]:
        return self.by_class.get(class_name or "", {})


def guarded_nodes(fi: FuncInfo, inventory: LockInventory):
    """Yield ``(node, frozenset(held canonical lock names))`` for every
    own node of ``fi``, tracking the lexical ``with self._lock:`` /
    ``with _lock:`` guard regions. Nested function bodies are excluded
    (they execute later, not under the lock)."""
    class_locks = inventory.class_locks(fi.class_name)

    def walk(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            child_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired = set()
                for item in child.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Attribute) \
                            and isinstance(ce.value, ast.Name) \
                            and ce.value.id == "self" \
                            and ce.attr in class_locks:
                        acquired.add(f"self.{class_locks[ce.attr]}")
                    elif isinstance(ce, ast.Name) \
                            and ce.id in inventory.module_locks:
                        acquired.add(ce.id)
                if acquired:
                    child_held = held | frozenset(acquired)
            yield child, child_held
            yield from walk(child, child_held)

    yield from walk(fi.node, frozenset())


def module_level_calls(tree: ast.AST):
    """Calls made at module import time (outside any function body —
    class bodies DO run at import)."""
    stack = [tree]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            stack.append(child)


# --------------------------------------------------------- tree iteration

def free_names(func_node: ast.AST) -> Set[str]:
    """Names referenced anywhere in a function (locals included — used
    to intersect enclosing taint into a closure, where over-approximation
    is safe)."""
    return {n.id for n in ast.walk(func_node) if isinstance(n, ast.Name)}


def iter_own_nodes(func_node: ast.AST):
    """Every node of a function body, *excluding* nested function/class
    bodies (those are separate analysis units)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def iter_own_calls(func_node: ast.AST):
    for node in iter_own_nodes(func_node):
        if isinstance(node, ast.Call):
            yield node


def map_call_args(call: ast.Call,
                  callee: FuncInfo) -> Dict[str, ast.AST]:
    """Map a call's arguments onto the callee's parameter names
    (``self`` dropped for attribute calls). Starred arguments make the
    positional mapping ambiguous — only keyword args are mapped then."""
    drop_self = isinstance(call.func, ast.Attribute) \
        and callee.params()[:1] in (["self"], ["cls"])
    pos = callee.params(drop_self=drop_self)
    mapped: Dict[str, ast.AST] = {}
    starred = any(isinstance(a, ast.Starred) for a in call.args)
    if not starred:
        for name, arg in zip(pos, call.args):
            mapped[name] = arg
    valid = set(pos) | set(callee.kwonly_params())
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in valid:
            mapped[kw.arg] = kw.value
    return mapped


# ----------------------------------------- string-literal provenance

def fstring_prefix(node: ast.JoinedStr) -> str:
    """The leading literal text of an f-string (everything before the
    first interpolation) — a dynamic metric name's checkable prefix."""
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            break
    return "".join(parts)


class StringProvenance:
    """Cross-module resolution of string-constant provenance: a
    ``Constant``, a ``Name`` bound by a module-level assignment or a
    ``from mod import NAME``, or an ``alias.NAME`` attribute whose
    alias an import statement binds to another project module.  The
    contract rules use this to see through constant indirection
    (``entry.update(status=mf.RUNNING)`` resolves to ``"running"``
    through the manifest module's ``RUNNING = contracts.SHARD_RUNNING``
    chain) without importing the code under analysis."""

    _MAX_DEPTH = 6

    def __init__(self, project: "Project"):
        self.project = project
        self._by_dotted: Dict[str, Module] = {}
        for m in project.modules:
            rel = m.rel
            if rel.endswith("/__init__.py"):
                name = rel[:-len("/__init__.py")].replace("/", ".")
            elif rel.endswith(".py"):
                name = rel[:-3].replace("/", ".")
            else:
                continue
            self._by_dotted[name] = m
        self._constants: Dict[int, Dict[str, ast.AST]] = {}
        self._imports: Dict[int, Dict[str, Tuple[str, Optional[str]]]] = {}

    def constants(self, module: Module) -> Dict[str, ast.AST]:
        """Module-level single-Name assignments (``NAME = <expr>``)."""
        cached = self._constants.get(id(module))
        if cached is None:
            cached = {}
            for node in module.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    cached[node.targets[0].id] = node.value
            self._constants[id(module)] = cached
        return cached

    def imports(self, module: Module) -> Dict[str,
                                              Tuple[str, Optional[str]]]:
        """Local binding -> (source module dotted name, member name).
        Member None = the binding IS the module (``import x as m`` /
        ``from pkg import mod``); else a ``from mod import NAME``."""
        cached = self._imports.get(id(module))
        if cached is not None:
            return cached
        cached = {}
        pkg_parts = module.rel.split("/")[:-1]
        if module.rel.endswith("/__init__.py"):
            pkg_parts = module.rel.split("/")[:-1]
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        cached[a.asname] = (a.name, None)
                    else:
                        cached[a.name.split(".")[0]] = \
                            (a.name.split(".")[0], None)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                else:
                    base = []
                base_name = ".".join(
                    base + (node.module.split(".") if node.module
                            else []))
                for a in node.names:
                    bind = a.asname or a.name
                    as_mod = f"{base_name}.{a.name}" if base_name \
                        else a.name
                    if as_mod in self._by_dotted:
                        cached[bind] = (as_mod, None)
                    else:
                        cached[bind] = (base_name, a.name)
        self._imports[id(module)] = cached
        return cached

    def resolve_str(self, module: Module, expr: ast.AST,
                    depth: int = 0) -> Optional[str]:
        """The string value ``expr`` provably holds, else None."""
        if depth > self._MAX_DEPTH or expr is None:
            return None
        if isinstance(expr, ast.Constant):
            return expr.value if isinstance(expr.value, str) else None
        if isinstance(expr, ast.Name):
            bound = self.constants(module).get(expr.id)
            if bound is not None:
                return self.resolve_str(module, bound, depth + 1)
            imp = self.imports(module).get(expr.id)
            if imp and imp[1] is not None:
                src = self._by_dotted.get(imp[0])
                if src is not None:
                    bound = self.constants(src).get(imp[1])
                    if bound is not None:
                        return self.resolve_str(src, bound, depth + 1)
            return None
        if isinstance(expr, ast.Attribute):
            alias = dotted(expr.value)
            if alias is None:
                return None
            imp = self.imports(module).get(alias)
            target = None
            if imp and imp[1] is None:
                target = self._by_dotted.get(imp[0])
            if target is None:
                target = self._by_dotted.get(alias)
            if target is not None:
                bound = self.constants(target).get(expr.attr)
                if bound is not None:
                    return self.resolve_str(target, bound, depth + 1)
            return None
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left = self.resolve_str(module, expr.left, depth + 1)
            right = self.resolve_str(module, expr.right, depth + 1)
            if left is not None and right is not None:
                return left + right
        return None

    def resolve_str_seq(self, module: Module,
                        expr: ast.AST) -> Optional[List[str]]:
        """Every element of a tuple/list literal resolved to strings
        (None when any element resists — a partial set would make the
        consuming rule silently blind to the unresolved entries)."""
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: List[str] = []
            for elt in expr.elts:
                v = self.resolve_str(module, elt)
                if v is None:
                    return None
                out.append(v)
            return out
        if isinstance(expr, ast.Call) and dotted(expr.func) in (
                "frozenset", "set", "tuple", "list") and expr.args:
            return self.resolve_str_seq(module, expr.args[0])
        return None
