"""AST plumbing shared by the graftlint rules.

Everything here is *static*: source files are parsed, never imported, so
the linter runs without jax (and on broken code). The central products:

- :class:`Project` — the parsed module set plus repo-aware indexes: a
  name-keyed function index, the set of jit-traced roots (``@jax.jit``
  and friends, plus Pallas kernels by their positional ``*_ref`` /
  keyword-only-static convention), tracer-reachability over the repo
  call graph, and an interprocedural **taint** of traced values that the
  ``tracer-leak`` rule consumes.
- :func:`dotted` — best-effort dotted name of an expression
  (``jax.jit``, ``os.environ.get``), the workhorse of call matching.

The taint model: a name is *traced* if it is a non-static parameter of a
jit-traced function, or derives from one through assignments, or is the
result of a ``jnp.`` / ``lax.`` / ``jax.`` call.  Shape/dtype attribute
reads and ``isinstance``/``len``/``type`` calls launder taint (their
results are static under tracing).  Taint flows across calls resolved in
the repo (positional and keyword args mapped onto the callee signature),
and into nested functions (tracing callbacks for ``scan``/``vmap``)
whose own parameters are traced by construction.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

JIT_NAMES = {"jax.jit", "jit"}
PARTIAL_NAMES = {"functools.partial", "partial"}
# attribute reads that are static under tracing (reading them off a
# tracer yields a concrete Python value)
STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}
# calls that are static under tracing even on traced operands
STATIC_CALLS = {"isinstance", "len", "type", "hasattr", "callable", "id",
                "repr", "str", "format"}
# call prefixes that produce traced values
TRACED_PREFIXES = ("jnp.", "lax.", "jax.", "pl.", "pltpu.")


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


@dataclass
class Module:
    path: pathlib.Path
    rel: str                      # posix path relative to the lint root
    tree: ast.AST
    lines: List[str]

    def line(self, n: int) -> str:
        return self.lines[n - 1] if 0 < n <= len(self.lines) else ""


def load_module(path: pathlib.Path, rel: str) -> Module:
    src = path.read_text(encoding="utf-8")
    return Module(path, rel, ast.parse(src, filename=str(path)),
                  src.splitlines())


@dataclass
class FuncInfo:
    module: Module
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    qualname: str
    parent: Optional["FuncInfo"]  # lexically enclosing function
    class_name: Optional[str]
    is_jit_root: bool = False
    is_kernel_root: bool = False
    static_argnames: Set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        return self.node.name

    def params(self, *, drop_self: bool = False) -> List[str]:
        a = self.node.args
        names = ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args])
        if drop_self and names and names[0] in ("self", "cls"):
            names = names[1:]
        return names

    def kwonly_params(self) -> List[str]:
        return [p.arg for p in self.node.args.kwonlyargs]

    def all_params(self) -> List[str]:
        extra = []
        if self.node.args.vararg:
            extra.append(self.node.args.vararg.arg)
        if self.node.args.kwarg:
            extra.append(self.node.args.kwarg.arg)
        return self.params() + self.kwonly_params() + extra


def _jit_decoration(dec: ast.AST) -> Optional[Set[str]]:
    """If ``dec`` marks a jit root, return its static_argnames set."""
    if dotted(dec) in JIT_NAMES:
        return set()
    if isinstance(dec, ast.Call):
        fn = dotted(dec.func)
        if fn in JIT_NAMES:
            return _static_argnames(dec)
        if fn in PARTIAL_NAMES and dec.args \
                and dotted(dec.args[0]) in JIT_NAMES:
            return _static_argnames(dec)
    return None


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
    return set()


def _is_pallas_kernel(node: ast.AST) -> bool:
    """Pallas kernels follow the repo convention: positional ``*_ref``
    parameters (Refs, traced) plus keyword-only static geometry."""
    names = [p.arg for p in node.args.posonlyargs + node.args.args]
    return any(n.endswith("_ref") for n in names)


class Project:
    """Parsed modules plus lazily-built repo-wide indexes."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.functions: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.func_of_node: Dict[ast.AST, FuncInfo] = {}
        self._index()
        self._taint: Optional[Dict[int, Set[str]]] = None
        self._reachable: Optional[Set[int]] = None
        self._logging: Optional[Set[int]] = None

    # ------------------------------------------------------------ indexing

    def _index(self) -> None:
        for mod in self.modules:
            self._index_scope(mod, mod.tree, None, None, prefix="")

    def _index_scope(self, mod, node, parent_fn, class_name, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                statics: Set[str] = set()
                is_jit = False
                for dec in child.decorator_list:
                    s = _jit_decoration(dec)
                    if s is not None:
                        is_jit, statics = True, s
                is_kernel = (not is_jit and child.name.endswith("_kernel")
                             and _is_pallas_kernel(child))
                fi = FuncInfo(mod, child, qual, parent_fn, class_name,
                              is_jit, is_kernel, statics)
                self.functions.append(fi)
                self.by_name.setdefault(child.name, []).append(fi)
                self.func_of_node[child] = fi
                self._index_scope(mod, child, fi, class_name,
                                  prefix=qual + ".")
            elif isinstance(child, ast.ClassDef):
                self._index_scope(mod, child, parent_fn, child.name,
                                  prefix=f"{prefix}{child.name}.")
            else:
                self._index_scope(mod, child, parent_fn, class_name, prefix)

    def resolve(self, call: ast.Call) -> List[FuncInfo]:
        """Candidate repo definitions for a call, by terminal name."""
        name = last_segment(dotted(call.func))
        return self.by_name.get(name, []) if name else []

    def enclosing(self, fi: FuncInfo) -> List[FuncInfo]:
        chain = []
        cur = fi.parent
        while cur is not None:
            chain.append(cur)
            cur = cur.parent
        return chain

    # --------------------------------------------------- jit roots + taint

    def roots(self) -> List[Tuple[FuncInfo, Set[str]]]:
        """(function, initially traced parameter names) for every
        jit-traced entry point: jit-decorated defs (non-static params)
        and Pallas kernels (positional Ref params)."""
        out = []
        for fi in self.functions:
            if fi.is_jit_root:
                traced = {p for p in fi.all_params()
                          if p not in fi.static_argnames
                          and p not in ("self", "cls")}
                out.append((fi, traced))
            elif fi.is_kernel_root:
                out.append((fi, set(fi.params())))
        return out

    def taints(self) -> Dict[int, Set[str]]:
        """Fixpoint map ``id(FuncInfo) -> traced local names`` over every
        tracer-reachable function (the side product is
        :meth:`reachable`)."""
        if self._taint is not None:
            return self._taint
        param_taint: Dict[int, Set[str]] = {}
        info: Dict[int, FuncInfo] = {}
        work: List[FuncInfo] = []

        def seed(fi: FuncInfo, names: Set[str]) -> None:
            key = id(fi)
            info[key] = fi
            prev = param_taint.get(key)
            if prev is None or not names <= prev:
                param_taint[key] = (prev or set()) | names
                if fi not in work:
                    work.append(fi)

        for fi, traced in self.roots():
            seed(fi, traced)

        final: Dict[int, Set[str]] = {}
        guard = 0
        while work and guard < 10000:
            guard += 1
            fi = work.pop(0)
            names = self._intra_taint(fi, param_taint[id(fi)])
            final[id(fi)] = names
            # propagate into repo callees through mapped arguments
            for call in iter_own_calls(fi.node):
                for callee in self.resolve(call):
                    mapped = map_call_args(call, callee)
                    if mapped is None:
                        continue
                    tainted_params = {
                        p for p, expr in mapped.items()
                        if expr is not None
                        and self.expr_tainted(expr, names)}
                    seed(callee, tainted_params)
            # directly nested defs: closure names carry the enclosing
            # taint; parameters are tainted by how the function is used —
            # direct calls map argument taint (handled above via
            # resolve()), while *escaping* uses (passed to scan/vmap/
            # pallas_call, stored) trace every parameter except ones a
            # functools.partial binds to untainted values
            for child in ast.walk(fi.node):
                sub = self.func_of_node.get(child)
                if sub is not None and sub.parent is fi:
                    seed(sub, names & free_names(sub.node))
                    esc = self._escape_taint(fi, sub, names)
                    if esc:
                        seed(sub, esc)
        self._taint = final
        self._reachable = set(final)
        return final

    def reachable(self) -> Set[int]:
        self.taints()
        return self._reachable or set()

    def _escape_taint(self, fi: FuncInfo, sub: FuncInfo,
                      names: Set[str]) -> Set[str]:
        """Traced parameters of nested ``sub`` implied by how ``fi``
        *uses* it beyond direct calls. A ``functools.partial(sub, ...)``
        binds the mapped params to the taint of the bound expressions;
        any other escaping reference (an argument to scan/vmap/
        pallas_call, an assignment) traces every parameter."""
        out: Set[str] = set()
        covered: Set[int] = set()
        params = sub.params()
        for call in iter_own_calls(fi.node):
            if isinstance(call.func, ast.Name) \
                    and call.func.id == sub.name:
                covered.add(id(call.func))  # direct call: mapped above
            elif dotted(call.func) in PARTIAL_NAMES and call.args \
                    and isinstance(call.args[0], ast.Name) \
                    and call.args[0].id == sub.name:
                covered.add(id(call.args[0]))
                bound: Dict[str, ast.AST] = {}
                for p, a in zip(params, call.args[1:]):
                    bound[p] = a
                for kw in call.keywords:
                    if kw.arg is not None:
                        bound[kw.arg] = kw.value
                for p in sub.all_params():
                    expr = bound.get(p)
                    if expr is None or self.expr_tainted(expr, names):
                        out.add(p)
        for node in iter_own_nodes(fi.node):
            if isinstance(node, ast.Name) and node.id == sub.name \
                    and id(node) not in covered:
                return out | set(sub.all_params())  # raw escape
        return out

    def _intra_taint(self, fi: FuncInfo, seeded: Set[str]) -> Set[str]:
        """Forward taint propagation over the function's own statements
        (nested defs excluded), iterated to a small fixpoint so loops
        converge."""
        tainted = set(seeded)
        for _ in range(10):
            grew = False
            for node in iter_own_nodes(fi.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    targets, value = [node.target], node.iter
                elif isinstance(node, ast.comprehension):
                    targets, value = [node.target], node.iter
                if value is None or not self.expr_tainted(value, tainted):
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) \
                                and n.id not in tainted:
                            tainted.add(n.id)
                            grew = True
            if not grew:
                break
        return tainted

    def expr_tainted(self, node: ast.AST, tainted: Set[str]) -> bool:
        """Does the expression's value derive from a traced value?"""
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value, tainted)
        if isinstance(node, ast.Call):
            fn = dotted(node.func)
            if fn in STATIC_CALLS:
                return False
            if fn and (fn.startswith(TRACED_PREFIXES) or fn in
                       ("vmap", "scan", "cond", "while_loop")):
                return True
            args = list(node.args) + [kw.value for kw in node.keywords]
            if isinstance(node.func, ast.Attribute) \
                    and self.expr_tainted(node.func.value, tainted):
                return True  # method call on a traced value
            return any(self.expr_tainted(a, tainted) for a in args)
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value, tainted)
        return any(self.expr_tainted(c, tainted)
                   for c in ast.iter_child_nodes(node)
                   if isinstance(c, ast.expr))

    # ------------------------------------------------------ logging closure

    DIRECT_LOG_NAMES = {"warn", "warning", "log_swallowed", "error",
                        "exception", "critical"}

    def _call_logs_directly(self, call: ast.Call) -> bool:
        fn = dotted(call.func)
        if fn == "warnings.warn":
            return True
        seg = last_segment(fn)
        # strip private-alias underscores: `_log_swallowed` is the same
        # sanctioned sink as `log_swallowed`
        if seg and seg.lstrip("_") in self.DIRECT_LOG_NAMES:
            return True
        # print(..., file=<not stdout>) is the stderr logging idiom
        if fn == "print":
            return any(kw.arg == "file" for kw in call.keywords)
        return False

    def logging_functions(self) -> Set[int]:
        """ids of repo functions that (transitively) emit a log line —
        the repo-aware half of the swallowed-exception rule."""
        if self._logging is not None:
            return self._logging
        logs: Set[int] = set()
        for fi in self.functions:
            for call in iter_own_calls(fi.node):
                if self._call_logs_directly(call):
                    logs.add(id(fi))
                    break
        changed = True
        guard = 0
        while changed and guard < 100:
            guard += 1
            changed = False
            for fi in self.functions:
                if id(fi) in logs:
                    continue
                for call in iter_own_calls(fi.node):
                    if any(id(c) in logs for c in self.resolve(call)):
                        logs.add(id(fi))
                        changed = True
                        break
        self._logging = logs
        return logs

    def call_is_logging(self, call: ast.Call) -> bool:
        if self._call_logs_directly(call):
            return True
        return any(id(c) in self.logging_functions()
                   for c in self.resolve(call))


# --------------------------------------------------------- tree iteration

def free_names(func_node: ast.AST) -> Set[str]:
    """Names referenced anywhere in a function (locals included — used
    to intersect enclosing taint into a closure, where over-approximation
    is safe)."""
    return {n.id for n in ast.walk(func_node) if isinstance(n, ast.Name)}


def iter_own_nodes(func_node: ast.AST):
    """Every node of a function body, *excluding* nested function/class
    bodies (those are separate analysis units)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def iter_own_calls(func_node: ast.AST):
    for node in iter_own_nodes(func_node):
        if isinstance(node, ast.Call):
            yield node


def map_call_args(call: ast.Call,
                  callee: FuncInfo) -> Dict[str, ast.AST]:
    """Map a call's arguments onto the callee's parameter names
    (``self`` dropped for attribute calls). Starred arguments make the
    positional mapping ambiguous — only keyword args are mapped then."""
    drop_self = isinstance(call.func, ast.Attribute) \
        and callee.params()[:1] in (["self"], ["cls"])
    pos = callee.params(drop_self=drop_self)
    mapped: Dict[str, ast.AST] = {}
    starred = any(isinstance(a, ast.Starred) for a in call.args)
    if not starred:
        for name, arg in zip(pos, call.args):
            mapped[name] = arg
    valid = set(pos) | set(callee.kwonly_params())
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in valid:
            mapped[kw.arg] = kw.value
    return mapped
