"""The compile-surface dataflow pack (round 18).

The repo's whole performance story — warm-path serve p50, the retrace
budgets, the hand-derived ``_warmup_shapes`` for both streaming
sessions — rests on an invariant nothing checked statically until now:
every jit dispatch geometry is bounded, bucketed, and covered by
warm-up, and no Python value leaks into a shape or dtype in a way that
retraces per call.  This module adds the dataflow layer that makes
those checks possible, plus the five rules built on it:

| rule                | catches                                          |
| ------------------- | ------------------------------------------------ |
| jit-shape-hazard    | an unbounded value (raw length, ``len()`` of a   |
|                     | runtime list, un-quantized arithmetic) reaching  |
|                     | a shape/dtype-determining parameter of a jit     |
|                     | root — every distinct value is a separate XLA    |
|                     | compile                                          |
| dtype-drift         | int16/uint16 SWAR lanes silently promoted to a   |
|                     | wider dtype across an op boundary                |
| jit-in-loop         | ``jax.jit`` (or a jit-decorated def) constructed |
|                     | per loop iteration — a fresh wrapper has an      |
|                     | empty cache, so every iteration recompiles       |
| warmup-coverage     | a dispatch-path geometry derivation not mirrored |
|                     | by the module's ``_warmup_shapes`` (an un-shared |
|                     | helper, or an inline pow2 loop either side)      |
| host-transfer-in-jit| implicit ``np.asarray``/``np.*`` on a tracer     |
|                     | path — a host transfer inside a traced function  |

The dataflow layer (:class:`CompileSurface`):

- **shape-determining parameters** — starting from the jit roots
  (``Project.roots()``): a root's ``static_argnames``, a Pallas
  kernel's keyword-only statics, and any parameter that flows (through
  the intraprocedural taint closure) into a shape slot — ``jnp.zeros``/
  ``broadcast_to``/``reshape`` dims, ``dtype=`` kwargs, Pallas
  ``grid=``/``BlockSpec`` arguments.  The property propagates *up* the
  unambiguous call graph: a function that forwards its own parameter
  into a shape-determining parameter of a callee is itself
  shape-determining in that parameter (``_launch_chunk_impl(max_len=
  ...)`` -> ``align_chain`` -> ``_nw_wavefront_kernel``).
- **origin classification** (:meth:`CompileSurface.classify`) — where a
  value passed at a dispatch site comes from: pow2 bucket quantizers
  and the repo's geometry helpers (:data:`QUANTIZER_NAMES`, plus any
  function whose body is a returned doubling loop), literals, module
  constants and instance attributes (fixed per engine) are *bounded*;
  raw lengths, ``len()`` of runtime collections and results of
  unrecognized repo calls are *unbounded*.  Parameters are "forwarded"
  — the finding lands at the caller that injects the unbounded value,
  once, not at every hop of the chain.

The runtime companion is ``racon_tpu/obs/compilewatch.py``: a
process-wide ``jax.monitoring`` listener attributes every real XLA
compile to (function, shape signature, phase, scope) — what these
rules prove statically, that proves (and reports) dynamically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import (FuncInfo, Module, Project, dotted, iter_own_calls,
                      iter_own_nodes, last_segment, map_call_args)
from .rules import Finding, Rule

# ------------------------------------------------------------- vocabulary

# array constructors whose leading positional argument is a shape (or a
# per-dim size): a value flowing here determines the compiled geometry
SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange", "iota",
               "broadcast_to", "tile", "reshape"}
# keyword names that are shape/dtype slots wherever they appear
SHAPE_KWARGS = {"shape", "dtype", "grid", "new_sizes", "dimensions",
                "num_warps", "block_shape"}
# call names that are shape slots in every argument (Pallas geometry)
SHAPE_CALLS_ALL_ARGS = {"BlockSpec", "GridSpec"}

# The repo's geometry quantizers: functions whose results take few
# distinct values per run by construction (pow2 rounding, bucket
# tables, budget caps).  A value derived from one of these is bounded;
# the set is curated per-repo (graftlint is repo-aware by design) and
# extended structurally by :func:`_doubling_loop_helpers` — any
# function that returns the target of a ``while X < ...: X *= 2``
# loop is a quantizer too.
QUANTIZER_NAMES = {
    # ops/nw.py
    "_pow2_at_least", "_sweep_bound", "_pad_batch", "_chunk_cap",
    "_seed_geometry", "_next_geometry", "_bucket_index",
    "chunk_dirs_budget",
    # ops/poa.py
    "_bucket_geometry", "_sweep_geometry", "cap_pairs_for",
    "bucket_L_for",
    # parallel/
    "mesh_size",
}

# Boolean variant selectors: repo predicates whose result takes at most
# two values, so a static/variant argument fed from one is bounded by
# construction (the SWAR/Pallas availability probes).  Recognized by
# naming convention — the same convention the probes follow.
_PREDICATE_SUFFIXES = ("_ok", "_fits", "_choice", "_enabled")
_PREDICATE_PREFIXES = ("is_", "has_", "use_")


def _is_predicate_name(name: Optional[str]) -> bool:
    if not name:
        return False
    bare = name.lstrip("_")
    return (name.endswith(_PREDICATE_SUFFIXES)
            or bare.startswith(_PREDICATE_PREFIXES))

# builtins that preserve boundedness when every argument is bounded
PASSTHRU_CALLS = {"min", "max", "abs", "int", "round", "sorted", "tuple",
                  "list", "divmod", "pow", "float", "bool"}
# calls whose result varies with runtime data volume — the unbounded
# primitives the issue class is about
UNBOUNDED_CALLS = {"len", "sum", "range", "enumerate", "count",
                   "perf_counter", "time", "monotonic"}

STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}

_MAX_DEPTH = 8


def _direct_names(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _doubling_loops(fi: FuncInfo) -> List[ast.While]:
    """``while X < ...: X *= 2`` loops in a function's own body — the
    inline pow2-quantization idiom."""
    out: List[ast.While] = []
    for node in iter_own_nodes(fi.node):
        if not isinstance(node, ast.While):
            continue
        test_names = _direct_names(node.test)
        for child in ast.walk(node):
            target: Optional[str] = None
            if isinstance(child, ast.AugAssign) \
                    and isinstance(child.op, ast.Mult) \
                    and isinstance(child.target, ast.Name) \
                    and isinstance(child.value, ast.Constant) \
                    and child.value.value == 2:
                target = child.target.id
            elif isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                name = child.targets[0].id
                for sub in ast.walk(child.value):
                    if isinstance(sub, ast.BinOp) \
                            and isinstance(sub.op, ast.Mult) \
                            and ((isinstance(sub.left, ast.Name)
                                  and sub.left.id == name
                                  and isinstance(sub.right, ast.Constant)
                                  and sub.right.value == 2)
                                 or (isinstance(sub.right, ast.Name)
                                     and sub.right.id == name
                                     and isinstance(sub.left, ast.Constant)
                                     and sub.left.value == 2)):
                        target = name
            if target is not None and target in test_names:
                out.append(node)
                break
    return out


def _returns_name(fi: FuncInfo, name: str) -> bool:
    """Does the function return ``name`` directly (or as a top-level
    tuple element)?  The helper-exemption for doubling loops: a
    returned loop target makes the function itself the shared
    quantizer; a loop whose result is consumed inline belongs in one."""
    for node in iter_own_nodes(fi.node):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        elts = v.elts if isinstance(v, ast.Tuple) else [v]
        for e in elts:
            if isinstance(e, ast.Name) and e.id == name:
                return True
            if isinstance(e, ast.Call):
                fn = last_segment(dotted(e.func))
                if fn in PASSTHRU_CALLS and any(
                        isinstance(a, ast.Name) and a.id == name
                        for a in e.args):
                    return True
    return False


# -------------------------------------------------------- dataflow layer

class CompileSurface:
    """Repo-wide compile-surface indexes, built lazily once per
    project (rules share one instance via :func:`get_surface`)."""

    def __init__(self, project: Project):
        self.project = project
        self._shape_params: Optional[Dict[int, Dict[str, str]]] = None
        self._quantizers: Optional[Set[str]] = None
        self._jit_reaching: Optional[Set[int]] = None

    # -------------------------------------------------------- quantizers

    def quantizers(self) -> Set[str]:
        """Names of the geometry-quantizer functions: the curated repo
        set plus every function structurally recognized as a returned
        doubling loop."""
        if self._quantizers is not None:
            return self._quantizers
        names = set(QUANTIZER_NAMES)
        for fi in self.project.functions:
            for loop in _doubling_loops(fi):
                tgt = self._loop_target(loop)
                if tgt and _returns_name(fi, tgt):
                    names.add(fi.name)
        self._quantizers = names
        return names

    @staticmethod
    def _loop_target(loop: ast.While) -> Optional[str]:
        for child in ast.walk(loop):
            if isinstance(child, ast.AugAssign) \
                    and isinstance(child.target, ast.Name):
                return child.target.id
            if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                return child.targets[0].id
        return None

    # ------------------------------------------- shape-determining params

    def shape_params(self) -> Dict[int, Dict[str, str]]:
        """``id(FuncInfo) -> {param: why}`` for every function whose
        parameter determines a compiled shape or dtype: jit roots
        (statics + shape-slot flow) and the repo functions that forward
        into them, to a fixpoint."""
        if self._shape_params is not None:
            return self._shape_params
        project = self.project
        marked: Dict[int, Dict[str, str]] = {}

        for fi, _traced in project.roots():
            params: Dict[str, str] = {}
            if fi.is_jit_root:
                for p in fi.static_argnames:
                    params[p] = "a static_argnames entry"
                # jit roots whose statics are keyword-only follow the
                # Pallas convention even without static_argnames
                if not fi.static_argnames:
                    for p in fi.kwonly_params():
                        params[p] = "a keyword-only static"
            elif fi.is_kernel_root:
                for p in fi.kwonly_params():
                    params[p] = "Pallas keyword-only static geometry"
            for p in fi.all_params():
                if p in params or p in ("self", "cls"):
                    continue
                slot = self._flows_into_shape_slot(fi, p)
                if slot:
                    params[p] = f"flows into {slot}"
            if params:
                marked[id(fi)] = params

        # propagate up the unambiguous call graph: a caller's own
        # parameter forwarded (by direct name reference) into a marked
        # parameter of a callee is itself shape-determining
        for _ in range(20):
            changed = False
            for fi in project.functions:
                own_params = set(fi.all_params()) - {"self", "cls"}
                if not own_params:
                    continue
                for call in iter_own_calls(fi.node):
                    callee = project.resolve_unique(call, fi)
                    if callee is None or id(callee) not in marked:
                        continue
                    mapped = map_call_args(call, callee)
                    for param in marked[id(callee)]:
                        expr = mapped.get(param)
                        if expr is None:
                            continue
                        for name in _direct_names(expr) & own_params:
                            mine = marked.setdefault(id(fi), {})
                            if name not in mine:
                                mine[name] = (f"forwarded into "
                                              f"`{callee.name}({param}=)`")
                                changed = True
            if not changed:
                break
        self._shape_params = marked
        return marked

    def _flows_into_shape_slot(self, fi: FuncInfo,
                               param: str) -> Optional[str]:
        derived = self.project._intra_taint(fi, {param})
        for call in iter_own_calls(fi.node):
            fn = dotted(call.func) or ""
            seg = last_segment(fn) or ""
            slots: List[ast.AST] = []
            if seg in SHAPE_CTORS:
                slots.extend(call.args[:1] if seg != "reshape"
                             else call.args)
            if seg in SHAPE_CALLS_ALL_ARGS:
                slots.extend(call.args)
            for kw in call.keywords:
                if kw.arg in SHAPE_KWARGS:
                    slots.append(kw.value)
            for slot in slots:
                if self._slot_names(slot) & derived:
                    return f"`{seg}` dims/dtype"
        return None

    @staticmethod
    def _slot_names(slot: ast.AST) -> Set[str]:
        """Names a shape slot genuinely depends on: reads of an array's
        own static geometry (``x.dtype`` as a ``dtype=`` kwarg,
        ``x.shape[0]`` as a dim) do not make ``x`` shape-determining —
        the array is a traced argument whose aval already keys the jit
        cache."""
        skip: Set[int] = set()
        for n in ast.walk(slot):
            if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
                for sub in ast.walk(n.value):
                    skip.add(id(sub))
        return {n.id for n in ast.walk(slot)
                if isinstance(n, ast.Name) and id(n) not in skip}

    # ------------------------------------------------------ jit reachability

    def jit_reaching(self) -> Set[int]:
        """ids of functions from which a jit/kernel root is reachable
        over the unambiguous call graph — the dispatch paths whose
        geometry derivations matter."""
        if self._jit_reaching is not None:
            return self._jit_reaching
        project = self.project
        # reversed edges: callee -> callers
        callers: Dict[int, List[int]] = {}
        for fi in project.functions:
            for call in iter_own_calls(fi.node):
                callee = project.resolve_unique(call, fi)
                if callee is not None:
                    callers.setdefault(id(callee), []).append(id(fi))
        reaching: Set[int] = {id(fi) for fi in project.functions
                              if fi.is_jit_root or fi.is_kernel_root}
        work = list(reaching)
        while work:
            k = work.pop()
            for caller in callers.get(k, ()):
                if caller not in reaching:
                    reaching.add(caller)
                    work.append(caller)
        self._jit_reaching = reaching
        return reaching

    # --------------------------------------------------- origin classification

    def classify(self, fi: FuncInfo, expr: ast.AST,
                 depth: int = 0) -> Tuple[bool, str, Set[str]]:
        """Classify where a value comes from: ``(bounded, why,
        helpers)``.  ``helpers`` collects the repo geometry functions
        seen along the derivation (consumed by warmup-coverage).  When
        unbounded, ``why`` names the offending source."""
        helpers: Set[str] = set()
        if depth > _MAX_DEPTH:
            return True, "depth-capped", helpers
        if isinstance(expr, ast.Constant):
            return True, "literal", helpers
        if isinstance(expr, ast.Name):
            return self._classify_name(fi, expr.id, depth, helpers)
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_ATTRS:
                return True, "array geometry attribute", helpers
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id in ("self", "cls"):
                return True, "instance attribute (fixed per engine)", \
                    helpers
            return self.classify(fi, expr.value, depth + 1)
        if isinstance(expr, ast.Subscript):
            return self.classify(fi, expr.value, depth + 1)
        if isinstance(expr, ast.Call):
            return self._classify_call(fi, expr, depth, helpers)
        if isinstance(expr, ast.Compare):
            # a comparison yields a boolean — two values, bounded no
            # matter how its operands vary
            return True, "boolean comparison", helpers
        if isinstance(expr, (ast.BinOp, ast.UnaryOp, ast.BoolOp,
                             ast.IfExp, ast.Tuple, ast.List)):
            for child in ast.iter_child_nodes(expr):
                if not isinstance(child, ast.expr):
                    continue
                ok, why, h = self.classify(fi, child, depth + 1)
                helpers |= h
                if not ok:
                    return False, why, helpers
            return True, "arithmetic over bounded values", helpers
        return True, "unmodelled expression", helpers

    def _classify_name(self, fi, name, depth, helpers):
        chain = [fi] + self.project.enclosing(fi)
        for f in chain:
            if name in f.all_params():
                return True, "forwarded parameter (checked at callers)", \
                    helpers
        assigned = False
        for f in chain:
            for node in iter_own_nodes(f.node):
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    tnames: Set[str] = set()
                    for t in node.targets:
                        tnames |= _direct_names(t)
                    if name in tnames:
                        value = node.value
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                        and name in _direct_names(node.target):
                    value = node.value
                elif isinstance(node, ast.NamedExpr) \
                        and isinstance(node.target, ast.Name) \
                        and node.target.id == name:
                    value = node.value
                elif isinstance(node, (ast.For, ast.AsyncFor)) \
                        and name in _direct_names(node.target):
                    value = node.iter
                if value is None:
                    continue
                assigned = True
                ok, why, h = self.classify(f, value, depth + 1)
                helpers |= h
                if not ok:
                    return False, f"`{name}` <- {why}", helpers
        if assigned:
            return True, f"`{name}` derives from bounded values", helpers
        # unassigned: a module constant or an import — bounded (module
        # constants are fixed at import; a rogue global would be
        # assigned somewhere the project can see)
        return True, f"`{name}` is a module-level constant/import", helpers

    def _classify_call(self, fi, call, depth, helpers):
        fn = dotted(call.func) or ""
        seg = last_segment(fn) or ""
        if seg in UNBOUNDED_CALLS:
            return False, (f"`{seg}()` of runtime data — its value "
                           f"varies per call"), helpers
        if seg in self.quantizers():
            helpers.add(seg)
            return True, f"quantized by `{seg}()`", helpers
        if seg in PASSTHRU_CALLS:
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                ok, why, h = self.classify(fi, a, depth + 1)
                helpers |= h
                if not ok:
                    return False, why, helpers
            return True, f"`{seg}()` of bounded values", helpers
        if _is_predicate_name(seg):
            return True, (f"boolean variant selector `{seg}()` "
                          f"(at most two values)"), helpers
        callee = self.project.resolve_unique(call, fi)
        if callee is not None:
            if callee.name in self.quantizers():
                helpers.add(callee.name)
                return True, f"quantized by `{callee.name}()`", helpers
            return False, (f"result of `{callee.name}()`, which is not "
                           f"a recognized geometry quantizer"), helpers
        # unresolved foreign call: permissive — bounded iff its inputs are
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            ok, why, h = self.classify(fi, a, depth + 1)
            helpers |= h
            if not ok:
                return False, why, helpers
        return True, "foreign call over bounded values", helpers


def get_surface(project: Project) -> CompileSurface:
    surf = getattr(project, "_compile_surface", None)
    if surf is None:
        surf = project._compile_surface = CompileSurface(project)
    return surf


# -------------------------------------------------------- jit-shape-hazard

class JitShapeHazardRule(Rule):
    """An unbounded value reaching a shape/dtype-determining parameter
    of a jit root (directly, or through the repo functions that forward
    into one) recompiles the kernel for every distinct value — the
    silent 30 s/chunk stealth tax the retrace budgets hunt at runtime.
    Geometry must route through the pow2/bucket quantizers; a value
    that is genuinely bounded for a non-obvious reason takes a reasoned
    pragma."""

    name = "jit-shape-hazard"
    blurb = ("an unbounded value (raw length, `len()` of a runtime list) reaching a shape/dtype-determining parameter of a jit root — every distinct value is a separate XLA compile")

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        surface = get_surface(project)
        marked = surface.shape_params()
        out: List[Finding] = []
        for fi in project.functions:
            if fi.module is not module:
                continue
            for call in iter_own_calls(fi.node):
                callee = project.resolve_unique(call, fi)
                if callee is None or id(callee) not in marked:
                    continue
                mapped = map_call_args(call, callee)
                for param, why in marked[id(callee)].items():
                    expr = mapped.get(param)
                    if expr is None:
                        continue
                    ok, uwhy, _h = surface.classify(fi, expr)
                    if ok:
                        continue
                    out.append(self.finding(
                        module, call,
                        f"`{param}` of `{callee.qualname}` is "
                        f"shape/dtype-determining ({why}) but receives "
                        f"an unbounded value ({uwhy}) — every distinct "
                        f"value is a separate XLA compile; quantize it "
                        f"through a pow2/bucket helper (or pragma with "
                        f"the bound)"))
        return out


# ------------------------------------------------------------ dtype-drift

class DtypeDriftRule(Rule):
    """int16/uint16 SWAR lanes silently promoted to a wider dtype by an
    op that mixes them with an int32/int64 operand: the promotion
    doubles lane width (halving VPU throughput) without any visible
    cast, and downstream kernels keep computing — just slower and off
    the packed path's bit-exactness contract.  Mixing must be explicit
    (``.astype``); a deliberate widening boundary takes a reasoned
    pragma."""

    name = "dtype-drift"
    blurb = ("int16/uint16 SWAR lanes silently promoted to a wider dtype across an op boundary")
    NARROW = {"int16", "uint16"}
    WIDE = {"int32", "uint32", "int64", "uint64"}
    MIXERS = {"where", "minimum", "maximum", "add", "subtract",
              "multiply", "bitwise_or", "bitwise_and", "bitwise_xor",
              "left_shift", "right_shift"}

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/ops/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for fi in project.functions:
            if fi.module is not module:
                continue
            widths = self._name_widths(fi)
            for node in iter_own_nodes(fi.node):
                msg = self._drift(node, widths)
                if msg:
                    out.append(self.finding(module, node, msg))
        return out

    @classmethod
    def _dtype_width(cls, expr: ast.AST) -> Optional[str]:
        """"narrow"/"wide" for a dtype expression (``jnp.int16``,
        ``np.uint16``, ``"int16"``), else None."""
        name = None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            name = expr.value
        else:
            name = last_segment(dotted(expr))
        if name in cls.NARROW:
            return "narrow"
        if name in cls.WIDE:
            return "wide"
        return None

    @classmethod
    def _call_width(cls, call: ast.Call) -> Optional[str]:
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("astype", "view") and call.args:
            return cls._dtype_width(call.args[0])
        for kw in call.keywords:
            if kw.arg == "dtype":
                return cls._dtype_width(kw.value)
        seg = last_segment(dotted(call.func))
        if seg == "arange" and not any(kw.arg == "dtype"
                                       for kw in call.keywords):
            return "wide"  # jnp.arange defaults to int32 on int args
        return None

    def _name_widths(self, fi: FuncInfo) -> Dict[str, str]:
        widths: Dict[str, str] = {}
        for _ in range(4):
            grew = False
            for node in iter_own_nodes(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                w = self._expr_width(node.value, widths)
                if w is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name) and widths.get(t.id) != w:
                        widths[t.id] = w
                        grew = True
            if not grew:
                break
        return widths

    def _expr_width(self, expr: ast.AST,
                    widths: Dict[str, str]) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return widths.get(expr.id)
        if isinstance(expr, ast.Call):
            w = self._call_width(expr)
            if w is not None:
                return w
            return None
        if isinstance(expr, (ast.Attribute, ast.Subscript)):
            return self._expr_width(expr.value, widths)
        if isinstance(expr, ast.BinOp):
            lw = self._expr_width(expr.left, widths)
            rw = self._expr_width(expr.right, widths)
            if "wide" in (lw, rw):
                return "wide"
            if "narrow" in (lw, rw):
                return "narrow"
        return None

    def _drift(self, node: ast.AST,
               widths: Dict[str, str]) -> Optional[str]:
        operands: List[ast.AST] = []
        what = None
        if isinstance(node, ast.BinOp):
            operands = [node.left, node.right]
            what = "arithmetic"
        elif isinstance(node, ast.Call):
            fn = dotted(node.func) or ""
            seg = last_segment(fn)
            if seg not in self.MIXERS:
                return None
            args = list(node.args)
            if seg == "where" and args:
                args = args[1:]  # the condition is bool, not a lane
            operands = args
            what = f"`{seg}`"
        else:
            return None
        seen = {self._expr_width(o, widths) for o in operands}
        if "narrow" in seen and "wide" in seen:
            return (f"int16/uint16 SWAR lane mixed with a wider operand "
                    f"in {what} — the lane is silently promoted to "
                    f"int32 across this op boundary (lane width doubles, "
                    f"VPU throughput halves); widen explicitly with "
                    f".astype or keep both operands narrow (or pragma "
                    f"a deliberate boundary with the reason)")
        return None


# ------------------------------------------------------------ jit-in-loop

class JitInLoopRule(Rule):
    """``jax.jit`` called — or a jit-decorated def defined — inside a
    loop body constructs a fresh jitted callable per iteration.  A
    fresh wrapper has an empty cache: every iteration traces and
    compiles again, a guaranteed cache miss that turns a warm loop into
    a compile loop.  Hoist the jitted function out of the loop; a
    deliberately per-iteration wrapper (a test probing compile
    behaviour) takes a reasoned pragma."""

    name = "jit-in-loop"
    blurb = ("`jax.jit` (or a jit-decorated def) constructed per loop iteration — guaranteed cache miss")
    JIT_CALLS = {"jax.jit", "jit"}

    def check(self, project: Project, module: Module) -> List[Finding]:
        from .astutil import _jit_decoration
        out: List[Finding] = []
        seen: Set[int] = set()
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for node in ast.walk(loop):
                if node is loop or id(node) in seen:
                    continue
                if isinstance(node, ast.Call) \
                        and dotted(node.func) in self.JIT_CALLS:
                    seen.add(id(node))
                    out.append(self.finding(
                        module, node,
                        "`jax.jit` constructed inside a loop — a fresh "
                        "wrapper has an empty cache, so every iteration "
                        "recompiles; hoist the jitted callable out of "
                        "the loop (or pragma with the reason)"))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if _jit_decoration(dec) is not None:
                            seen.add(id(node))
                            out.append(self.finding(
                                module, node,
                                f"jit-decorated `{node.name}` defined "
                                f"inside a loop — each iteration builds "
                                f"a new jitted callable with an empty "
                                f"cache; hoist the definition (or "
                                f"pragma with the reason)"))
                            break
        return out


# -------------------------------------------------------- warmup-coverage

class WarmupCoverageRule(Rule):
    """In a module that carries a ``_warmup_shapes`` derivation (the
    device engines), every dispatch-path geometry derivation must be
    *mirrored* by it — shared helpers, not parallel re-implementations.
    Two drift shapes are caught: (a) a geometry helper called on a
    jit-reaching dispatch path that ``_warmup_shapes`` never
    (transitively) calls — the warm-up cannot mirror that dispatch
    shape and the first real dispatch compiles cold; (b) an inline
    ``while X < ...: X *= 2`` quantization loop (on either side) whose
    logic necessarily drifts from the helper the other side uses.  The
    ``_AlignStream``/``_ConsensusStream`` warm-up drift class of rounds
    13-17, checked instead of re-derived by hand.  A deliberately
    uncovered derivation (data-dependent escalation rungs) takes a
    reasoned pragma."""

    name = "warmup-coverage"
    blurb = ("a dispatch-path geometry derivation not mirrored by `_warmup_shapes` (an unshared helper, or an inline pow2 loop on either side)")
    WARM_NAME = "_warmup_shapes"

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/ops/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        warm_roots = [fi for fi in project.functions
                      if fi.module is module and fi.name == self.WARM_NAME]
        if not warm_roots:
            return []
        surface = get_surface(project)
        quantizers = surface.quantizers()
        warm_names = self._closure_names(project, warm_roots)
        reaching = surface.jit_reaching()
        out: List[Finding] = []
        for fi in project.functions:
            if fi.module is not module:
                continue
            in_warm = fi.name in warm_names or self._under_warmup(fi)
            # (b) inline doubling loops: flagged on BOTH sides (and in
            # the pack path between them) — the quantization belongs in
            # one shared helper
            if fi.name not in quantizers:
                for loop in _doubling_loops(fi):
                    side = ("the warm-up derivation" if in_warm
                            else "the dispatch/pack path")
                    out.append(self.finding(
                        module, loop,
                        f"inline pow2 quantization in {side} "
                        f"(`{fi.qualname}`) — extract the loop into "
                        f"a helper shared with "
                        f"{self.WARM_NAME} so the dispatch and "
                        f"warm-up geometries cannot drift"))
            if in_warm or id(fi) not in reaching:
                continue
            # (a) dispatch-path helpers the warm-up never calls
            for call in iter_own_calls(fi.node):
                callee = project.resolve_unique(call, fi)
                if callee is None or callee.name not in quantizers:
                    continue
                if callee.name in warm_names:
                    continue
                out.append(self.finding(
                    module, call,
                    f"dispatch-path geometry in `{fi.qualname}` derives "
                    f"via `{callee.name}()`, which {self.WARM_NAME} "
                    f"never calls — warm-up cannot mirror this dispatch "
                    f"shape and its first real dispatch compiles cold "
                    f"(share the helper, or pragma why the geometry is "
                    f"covered)"))
        return out

    @staticmethod
    def _closure_names(project: Project,
                       roots: List[FuncInfo]) -> Set[str]:
        names: Set[str] = set()
        work = list(roots)
        seen: Set[int] = set()
        while work:
            fi = work.pop()
            if id(fi) in seen:
                continue
            seen.add(id(fi))
            names.add(fi.name)
            for call in iter_own_calls(fi.node):
                callee = project.resolve_unique(call, fi)
                if callee is not None and id(callee) not in seen:
                    work.append(callee)
        return names

    @staticmethod
    def _under_warmup(fi: FuncInfo) -> bool:
        cur: Optional[FuncInfo] = fi
        while cur is not None:
            if "warmup" in cur.name or cur.name.startswith("_warm"):
                return True
            cur = cur.parent
        return False


# --------------------------------------------------- host-transfer-in-jit

class HostTransferInJitRule(Rule):
    """A ``np.*`` call on a traced value inside a jit-reachable
    function is an implicit host transfer: at trace time it either
    fails outright or silently concretizes one batch's values into the
    compiled program (the sibling of tracer-leak's explicit casts, via
    numpy's __array__ protocol instead).  Device code computes with
    ``jnp``; host fetches happen after dispatch, through the sanctioned
    fetch paths — never inside a traced function."""

    name = "host-transfer-in-jit"
    blurb = ("implicit `np.asarray`/`np.*` on a tracer path inside jit-reachable functions")
    NP_PREFIXES = ("np.", "numpy.")

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        taints = project.taints()
        for fi in project.functions:
            if fi.module is not module or id(fi) not in taints:
                continue
            tainted = taints[id(fi)]
            for call in iter_own_calls(fi.node):
                fn = dotted(call.func) or ""
                if not fn.startswith(self.NP_PREFIXES):
                    continue
                args = list(call.args) + [kw.value for kw in
                                          call.keywords]
                if any(project.expr_tainted(a, tainted) for a in args):
                    out.append(self.finding(
                        module, call,
                        f"`{fn}` on a traced value in jit-reachable "
                        f"`{fi.qualname}` — an implicit host transfer "
                        f"on the tracer path (fails at trace time or "
                        f"bakes one batch's values into the compiled "
                        f"program); compute with jnp, fetch after "
                        f"dispatch"))
        return out


COMPILE_SURFACE_RULES = [JitShapeHazardRule(), DtypeDriftRule(),
                         JitInLoopRule(), WarmupCoverageRule(),
                         HostTransferInJitRule()]
