"""The concurrency & durability rule pack (round 15).

Everything the round-8 rules could not see: the chip-worker pools and
lease keepers in ``exec/``, the socket/worker/heartbeat threads in
``serve/``, thread-local metric scopes in ``obs/``, and the manifest
durability protocol kill-then-resume correctness depends on.  All five
rules consume the concurrency layer in :mod:`tools.analysis.astutil`
(thread entry-point discovery, execution contexts, lock inventories,
guard regions, the blocking-call closure).

| rule                    | catches                                      |
| ----------------------- | -------------------------------------------- |
| lock-discipline         | a self attribute written from >=2 execution  |
|                         | contexts (thread roots / the main path) with |
|                         | no common guarding lock                      |
| blocking-under-lock     | sleep / socket I/O / subprocess / fsync /    |
|                         | device sync / bounded-queue get-put while a  |
|                         | named lock is held (directly or through a    |
|                         | transitively-blocking repo function)         |
| atomic-write-discipline | raw write-mode ``open()`` in the durability- |
|                         | critical packages; tmp->rename+fsync writers |
|                         | are allowlisted                              |
| thread-lifecycle        | threads started with no join and no          |
|                         | stop-event wiring (leak / lost-write at exit)|
| scope-discipline        | metric writes naming the ``job.`` scope by   |
|                         | hand instead of ``metrics.job_scope``        |

The runtime companion is the lock-order witness in
``racon_tpu/sanitize.py`` (``RACON_TPU_SANITIZE=1``): the named locks
these rules reason about statically are wrapped at runtime and their
acquisition-order graph is checked for cycles at process exit.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .astutil import (MAIN_CONTEXT, FuncInfo, Module, Project, dotted,
                      guarded_nodes, iter_own_calls, iter_own_nodes,
                      last_segment)
from .rules import Finding, Rule


def _fmt_contexts(contexts: Set[str]) -> str:
    return ", ".join(sorted(contexts))


# --------------------------------------------------------- lock-discipline

class LockDisciplineRule(Rule):
    """A ``self.X`` attribute assigned from two or more execution
    contexts — distinct thread roots, or a thread root and the main
    path — with no lock common to every write site is a data race (or,
    at best, an undocumented reliance on the GIL's per-bytecode
    atomicity).  ``__init__`` writes are exempt (``Thread.start()`` is
    a happens-before edge), as are the lock/condition attributes
    themselves.  A deliberately unguarded write (a slot drained by
    exactly one thread, a monotonic watchdog timestamp) takes a
    reasoned pragma."""

    name = "lock-discipline"
    blurb = ("a shared attribute written from ≥2 execution contexts (thread roots / main path) with no common guarding lock")
    SKIP_METHODS = {"__init__", "__new__", "__post_init__"}

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        contexts = project.exec_contexts()
        inventory = project.lock_inventory(module)
        # (class, attr) -> [(site node, held locks, site contexts)]
        writes: Dict[Tuple[str, str], List] = {}
        for fi in project.functions:
            if fi.module is not module or not fi.class_name \
                    or fi.name in self.SKIP_METHODS:
                continue
            lock_attrs = set(inventory.class_locks(fi.class_name))
            ctx = contexts.get(id(fi), set())
            for node, held in guarded_nodes(fi, inventory):
                for attr in self._written_attrs(node):
                    if attr in lock_attrs:
                        continue
                    writes.setdefault((fi.class_name, attr), []).append(
                        (node, held, ctx))
        out: List[Finding] = []
        for (cls, attr), sites in sorted(writes.items()):
            all_ctx: Set[str] = set()
            for _, _, ctx in sites:
                all_ctx |= ctx
            if len(all_ctx) < 2:
                continue
            common = frozenset.intersection(
                *[frozenset(held) for _, held, _ in sites])
            if common:
                continue
            # report at the first *unguarded* site (the fix target)
            node = next((n for n, held, _ in sites if not held),
                        sites[0][0])
            out.append(self.finding(
                module, node,
                f"`{cls}.{attr}` is written from "
                f"{len(all_ctx)} execution contexts "
                f"({_fmt_contexts(all_ctx)}) with no common guarding "
                f"lock — hold one lock across every write (or pragma "
                f"with the reason the race is benign)"))
        return out

    @staticmethod
    def _written_attrs(node: ast.AST):
        """Names of ``self.X`` (or ``self.X[...]``) assignment targets
        of ``node``."""
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                if isinstance(el, ast.Subscript):
                    el = el.value
                if isinstance(el, ast.Attribute) \
                        and isinstance(el.value, ast.Name) \
                        and el.value.id == "self":
                    yield el.attr


# ----------------------------------------------------- blocking-under-lock

class BlockingUnderLockRule(Rule):
    """A blocking call made while a named lock is held stalls every
    thread contending for that lock (and, for the serve/exec
    registries, the whole scheduler): ``time.sleep``, socket
    send/recv/accept, ``subprocess``, ``os.fsync``,
    ``block_until_ready``, Event ``.wait``, bounded-queue ``get``/
    ``put`` — directly, or through a repo function that transitively
    blocks (the ``save_manifest -> durable_write -> fsync`` chain).
    ``Condition.wait`` releases its lock and is exempt.  A hold that
    exists precisely to serialize the blocking operation (the manifest
    snapshot writer) takes a reasoned pragma."""

    name = "blocking-under-lock"
    blurb = ("sleep / socket I/O / `subprocess` / fsync / device sync / bounded-queue get-put while a named lock is held (transitively too)")

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        inventory = project.lock_inventory(module)
        out: List[Finding] = []
        for fi in project.functions:
            if fi.module is not module:
                continue
            for node, held in guarded_nodes(fi, inventory):
                if not held or not isinstance(node, ast.Call):
                    continue
                why = project.call_blocks(node, fi)
                if why is None:
                    continue
                out.append(self.finding(
                    module, node,
                    f"blocking call {why} while holding "
                    f"{_fmt_contexts(set(held))} in `{fi.qualname}` — "
                    f"move it outside the lock (or pragma with why the "
                    f"hold must cover it)"))
        return out


# ------------------------------------------------- atomic-write-discipline

class AtomicWriteDisciplineRule(Rule):
    """In the durability-critical packages (``exec``, ``serve``,
    ``obs``), every write-mode ``open()`` must be one of the durable
    protocols or route through them: the tmp -> fsync -> atomic-rename
    protocol (``manifest.atomic_write`` / ``durable_write`` /
    ``report.atomic_write_bytes``) for whole artifacts, or — round 16,
    the job journal's pattern — the **fsync'd-append** protocol: an
    append-mode open whose records go through ``os.fsync`` /
    ``manifest.append_durable`` (in the opening function or a sibling
    method of the same class, the handle-caching journal shape).  A
    raw ``open(path, "wb")`` can leave a torn artifact that a resume
    or a concurrent worker then trusts; a raw un-fsync'd append can
    silently lose acknowledged records.  Allowlisted: functions that
    open a ``*.tmp*`` name and ``os.replace``/``os.rename`` it into
    place (the protocol's own writers), and fsync'd appenders.  A
    deliberately raw write (a re-derivable scratch file) takes a
    reasoned pragma."""

    name = "atomic-write-discipline"
    blurb = ("raw write-mode `open()` in the durability-critical packages (tmp→fsync→rename writers allowlisted)")
    WRITE_MODES = ("w", "a", "x")
    APPEND_SYNCERS = ("os.fsync", "append_durable")

    def applies(self, rel: str) -> bool:
        return rel.endswith(".py") and rel.startswith(
            ("racon_tpu/exec/", "racon_tpu/serve/", "racon_tpu/obs/"))

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for fi in project.functions:
            if fi.module is not module:
                continue
            allowlisted = self._renames_tmp(fi)
            for call in iter_own_calls(fi.node):
                if dotted(call.func) != "open" or not call.args:
                    continue
                mode = (call.args[1] if len(call.args) >= 2 else
                        next((kw.value for kw in call.keywords
                              if kw.arg == "mode"), None))
                if mode is None or not (isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and mode.value.startswith(self.WRITE_MODES)):
                    continue
                if allowlisted and self._is_tmp_name(fi, call.args[0]):
                    continue
                if mode.value.startswith("a") and \
                        self._append_synced(project, fi):
                    continue
                out.append(self.finding(
                    module, call,
                    f"raw `open(..., {mode.value!r})` in "
                    f"`{fi.qualname}` bypasses the durable-write "
                    f"protocol — route through "
                    f"manifest.atomic_write/durable_write or "
                    f"report.atomic_write_bytes (append-mode: fsync "
                    f"every record via manifest.append_durable), or "
                    f"pragma a re-derivable scratch file with the "
                    f"reason"))
        return out

    @staticmethod
    def _renames_tmp(fi: FuncInfo) -> bool:
        return any(dotted(c.func) in ("os.replace", "os.rename")
                   for c in iter_own_calls(fi.node))

    @classmethod
    def _append_synced(cls, project: Project, fi: FuncInfo) -> bool:
        """The fsync'd-append allowlist: the opening function — or,
        for the journal's cached-handle shape, a sibling method of the
        same class — pushes records through ``os.fsync`` /
        ``append_durable``, so every acknowledged append is on disk."""
        if fi.class_name is None:
            scope = [fi]
        else:
            scope = [f for f in project.functions
                     if f.module is fi.module
                     and f.class_name == fi.class_name]
        for f in scope:
            for call in iter_own_calls(f.node):
                name = dotted(call.func) or ""
                if name in cls.APPEND_SYNCERS or \
                        last_segment(name) == "append_durable" or \
                        name.endswith(".fsync"):
                    return True
        return False

    @staticmethod
    def _is_tmp_name(fi: FuncInfo, expr: ast.AST) -> bool:
        """Does the opened path (or the local it names) carry a
        ``.tmp`` marker — the tmp half of tmp -> rename?"""

        def has_tmp(e: ast.AST) -> bool:
            for n in ast.walk(e):
                if isinstance(n, ast.Constant) \
                        and isinstance(n.value, str) and ".tmp" in n.value:
                    return True
            return False

        if has_tmp(expr):
            return True
        if isinstance(expr, ast.Name):
            for node in iter_own_nodes(fi.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == expr.id
                        for t in node.targets) and has_tmp(node.value):
                    return True
        return False


# ----------------------------------------------------------- thread-lifecycle

class ThreadLifecycleRule(Rule):
    """Every started thread needs an owner: either its entry point
    loops on a stop/abort event (``self._stop.wait(...)`` /
    ``.is_set()`` — the daemon-with-shutdown pattern, checked one call
    level deep since round 16: a supervisor-restartable worker loop
    whose scheduling helper polls the stop event counts as wired), or
    something in the spawning class/module ``join()``s it.  A
    fire-and-forget non-daemon thread hangs interpreter exit; a
    fire-and-forget daemon thread is killed mid-write at exit with no
    flush.  A deliberately abandoned thread (a droppable best-effort
    warm-up) takes a reasoned pragma."""

    name = "thread-lifecycle"
    blurb = ("threads started with no join and no stop-event wiring")

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for spawn in project.thread_spawns():
            if spawn.module is not module:
                continue
            if any(self._stop_wired(project, t)
                   for t in spawn.targets):
                continue
            if self._scope_joins(project, spawn):
                continue
            what = (spawn.targets[0].qualname if spawn.targets
                    else "<unresolved target>")
            out.append(self.finding(
                module, spawn.call,
                f"thread running `{what}` is started without join-or-"
                f"abort-event wiring — join it, or loop its body on a "
                f"stop event (or pragma with why abandoning it is "
                f"safe)"))
        return out

    @staticmethod
    def _polls_stop(target: FuncInfo) -> bool:
        """Does the entry point's own body poll a stop/abort signal?"""
        for call in iter_own_calls(target.node):
            if not isinstance(call.func, ast.Attribute):
                continue
            recv = (dotted(call.func.value) or "").lower()
            if call.func.attr in ("wait", "is_set") \
                    and ("stop" in recv or "abort" in recv):
                return True
        return False

    @classmethod
    def _stop_wired(cls, project: Project, target: FuncInfo,
                    depth: int = 1) -> bool:
        """Stop-event wiring, directly or one ``self.m()`` call deep —
        the supervisor-restartable worker-loop shape (round 16): the
        entry loops forever but its blocking scheduler helper
        (``self._next_job``) is what polls the stop event."""
        if cls._polls_stop(target):
            return True
        if depth <= 0 or target.class_name is None:
            return False
        for call in iter_own_calls(target.node):
            if not (isinstance(call.func, ast.Attribute)
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id == "self"):
                continue
            for cand in project.by_name.get(call.func.attr, ()):
                if cand.module is target.module \
                        and cand.class_name == target.class_name \
                        and cls._polls_stop(cand):
                    return True
        return False

    @staticmethod
    def _scope_joins(project: Project, spawn) -> bool:
        """Is a bare ``.join()`` (0-1 args — Thread.join, not
        str.join) called anywhere in the spawning class (or, for a
        module-level/function spawn, the module)?"""
        spawner = spawn.spawner
        cls = spawner.class_name if spawner else None
        for fi in project.functions:
            if fi.module is not spawn.module:
                continue
            if cls is not None and fi.class_name != cls:
                continue
            for call in iter_own_calls(fi.node):
                if not (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "join"):
                    continue
                # Thread.join takes no args or a numeric timeout;
                # str.join takes exactly one iterable — a non-numeric
                # argument (or a str-literal receiver) is string work
                if isinstance(call.func.value, ast.Constant):
                    continue
                if not call.args or (
                        len(call.args) == 1
                        and isinstance(call.args[0], ast.Constant)
                        and isinstance(call.args[0].value, (int, float))):
                    return True
        return False


# ----------------------------------------------------------- scope-discipline

class ScopeDisciplineRule(Rule):
    """The ``job.<id>.`` metric namespace belongs to
    ``metrics.job_scope`` / ``metrics.clear_job``: a hand-built
    ``job.`` name written through ``inc``/``set_gauge``/``add_time``/
    ``set_scope``/``clear`` bypasses the thread-local scoping that
    keeps concurrent service jobs' metrics disjoint (and silently
    collides with a real job id).  Reads are exempt — aggregators pass
    the scope string around legitimately."""

    name = "scope-discipline"
    blurb = ("hand-built `job.` metric names bypassing `metrics.job_scope`")
    WRITERS = {"inc", "set_gauge", "add_time", "set_scope", "clear"}
    PREFIX = "job."

    def applies(self, rel: str) -> bool:
        return (rel.startswith("racon_tpu/") and rel.endswith(".py")
                and rel != "racon_tpu/obs/metrics.py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if last_segment(dotted(node.func)) not in self.WRITERS:
                continue
            arg = node.args[0]
            if self._literal_job_name(arg):
                out.append(self.finding(
                    module, node,
                    f"metric write names the `{self.PREFIX}` scope by "
                    f"hand — build job-scoped names with "
                    f"metrics.job_scope(...) (and drop them with "
                    f"metrics.clear_job), never with literals"))
        return out

    @classmethod
    def _literal_job_name(cls, arg: ast.AST) -> bool:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value.startswith(cls.PREFIX)
        if isinstance(arg, ast.JoinedStr) and arg.values:
            head = arg.values[0]
            return (isinstance(head, ast.Constant)
                    and isinstance(head.value, str)
                    and head.value.startswith(cls.PREFIX))
        return False


CONCURRENCY_RULES = [LockDisciplineRule(), BlockingUnderLockRule(),
                     AtomicWriteDisciplineRule(), ThreadLifecycleRule(),
                     ScopeDisciplineRule()]
