"""The contract pack: string-keyed registries and lifecycle machines
become statically checked invariants.

Five rules, all checking emission/consumption sites against the ONE
declarative registry ``racon_tpu/contracts.py`` (stdlib-only, imported
by the rules the same way env-flag-registry loads the flag registry):

| rule                | catches                                        |
| ------------------- | ---------------------------------------------- |
| metric-registry     | metrics.inc/set_gauge/add_time names that      |
|                     | break the grammar, are unregistered, or carry  |
|                     | an unregistered dynamic (f-string) prefix      |
| span-registry       | obs.span names not declared in SPANS (a silent |
|                     | rename orphans the report's span-timer reads)  |
| fault-site-registry | FAULT_SITES entries with no faults.check site  |
|                     | or no test that injects them                   |
| schema-coherence    | report-section emitters whose dict keys drift  |
|                     | from the schema key sets — both directions     |
| state-transition    | journal appends / job+shard state writes that  |
|                     | mint undeclared states or encode undeclared    |
|                     | machine edges (e.g. collected->running)        |

String names are resolved through project-wide constant provenance
(:class:`tools.analysis.astutil.StringProvenance`): a literal, a
module constant, a cross-module ``alias.NAME`` chain, or an f-string's
literal prefix.  Unresolvable names are skipped, never guessed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .astutil import (Module, Project, dotted, fstring_prefix,
                      last_segment)
from .rules import Finding, Rule


def _contracts():
    """The live registry (racon_tpu.contracts is stdlib-only, so this
    is safe anywhere the linter runs); None disables the pack."""
    try:
        import racon_tpu.contracts as c
        return c
    # graftlint: disable=swallowed-exception (lint must run without the repo importable)
    except Exception:
        return None


# ---------------------------------------------------------- metric-registry

class MetricRegistryRule(Rule):
    """Every ``metrics.inc/set_gauge/add_time`` name must parse under
    the metric grammar and land in the registry: static names in
    ``contracts.METRICS``, dynamic (f-string) names under a registered
    ``contracts.DYNAMIC_METRIC_PREFIXES`` prefix.  Names the resolver
    cannot prove (a plain variable, e.g. the span exit's
    ``self.name``) are skipped — the span-registry rule closes that
    hole at the point the name is minted."""

    name = "metric-registry"
    blurb = ("`metrics.inc/set_gauge/add_time` names that break the metric grammar, are unregistered, or carry an unregistered dynamic prefix (`racon_tpu/contracts.py`)")
    EMITTERS = {"inc", "set_gauge", "add_time"}

    def applies(self, rel: str) -> bool:
        return ((rel.startswith("racon_tpu/") or rel == "bench.py")
                and rel != "racon_tpu/obs/metrics.py"
                and rel.endswith(".py"))

    def check(self, project: Project, module: Module) -> List[Finding]:
        c = _contracts()
        if c is None:
            return []
        prov = project.provenance()
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = dotted(node.func)
            if last_segment(fn) not in self.EMITTERS:
                continue
            if fn not in self.EMITTERS \
                    and not fn.endswith(tuple("metrics." + e
                                              for e in self.EMITTERS)):
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.JoinedStr):
                prefix = fstring_prefix(arg0)
                if not prefix:
                    out.append(self.finding(
                        module, node,
                        f"dynamic metric name passed to `{fn}` has no "
                        f"literal prefix — nothing to check against "
                        f"contracts.DYNAMIC_METRIC_PREFIXES"))
                elif not prefix.startswith(
                        tuple(c.DYNAMIC_METRIC_PREFIXES)):
                    out.append(self.finding(
                        module, node,
                        f"dynamic metric prefix {prefix!r} is not "
                        f"registered in contracts."
                        f"DYNAMIC_METRIC_PREFIXES"))
                continue
            name = prov.resolve_str(module, arg0)
            if name is None:
                continue
            if not c.METRIC_NAME_RE.match(name):
                out.append(self.finding(
                    module, node,
                    f"metric name {name!r} violates the name grammar "
                    f"(lowercase dotted segments, contracts."
                    f"METRIC_NAME_RE)"))
            elif name not in c.METRICS:
                out.append(self.finding(
                    module, node,
                    f"metric {name!r} is not registered in "
                    f"racon_tpu/contracts.py METRICS"))
        return out


# ------------------------------------------------------------ span-registry

class SpanRegistryRule(Rule):
    """Every ``obs.span`` name must be declared in ``contracts.SPANS``.
    Span exits land in the metrics timers keyed by the span name and
    the run report's dispatch-vs-fetch splits read those timers BY
    NAME — so a silently renamed span zeroes a report column without
    failing anything.  Now the rename fails here."""

    name = "span-registry"
    blurb = ("`obs.span` names not declared in `contracts.SPANS` — a silent span rename orphans the report's span-timer reads")
    SPAN_CALLS = {"obs.span", "span", "trace.span", "obs.trace.span"}

    def applies(self, rel: str) -> bool:
        return (rel.startswith("racon_tpu/") and rel.endswith(".py")
                and not rel.startswith("racon_tpu/obs/"))

    def check(self, project: Project, module: Module) -> List[Finding]:
        c = _contracts()
        if c is None:
            return []
        prov = project.provenance()
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if dotted(node.func) not in self.SPAN_CALLS:
                continue
            name = prov.resolve_str(module, node.args[0])
            if name is not None and name not in c.SPANS:
                out.append(self.finding(
                    module, node,
                    f"span {name!r} is not declared in "
                    f"racon_tpu/contracts.py SPANS — the report's "
                    f"span-timer reads would silently miss it"))
        return out


# ----------------------------------------------------- fault-site-registry

class FaultSiteRegistryRule(Rule):
    """Every declared fault site must have BOTH halves of its chaos
    contract: a ``faults.check("<site>")`` injection point somewhere in
    the tree, and at least one test that actually injects it (a
    ``"<site>:"`` spec literal in tests/).  A site with no check call
    is dead registry; a site no test injects is an untested failure
    path — the kind that works until the one production day it
    matters.  Anchored to the FAULT_SITES declaration so each site's
    finding lands on its own tuple element line."""

    name = "fault-site-registry"
    blurb = ("a declared fault site with no `faults.check` injection point, or one no test injects")

    def applies(self, rel: str) -> bool:
        return rel == "racon_tpu/contracts.py"

    def check(self, project: Project, module: Module) -> List[Finding]:
        prov = project.provenance()
        assign = None
        for node in module.tree.body:
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target]
                       if isinstance(node, ast.AnnAssign) else [])
            if any(isinstance(t, ast.Name) and t.id == "FAULT_SITES"
                   for t in targets):
                assign = node
                break
        if assign is None or not isinstance(assign.value,
                                            (ast.Tuple, ast.List)):
            return []
        sites: List[Tuple[str, ast.AST]] = []
        for elt in assign.value.elts:
            v = prov.resolve_str(module, elt)
            if v is not None:
                sites.append((v, elt))
        checked = set()
        for m in project.modules:
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Call) and node.args:
                    fn = dotted(node.func)
                    if fn and (fn == "check"
                               or fn.endswith("faults.check")):
                        v = prov.resolve_str(m, node.args[0])
                        if v is not None:
                            checked.add(v)
        # injection specs live in tests; a single-file selftest project
        # has no tests/ modules, so the fixture itself is scanned
        test_mods = [m for m in project.modules
                     if m.rel.startswith("tests/")]
        if not test_mods:
            test_mods = list(project.modules)
        injected = set()
        for m in test_mods:
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    for site, _ in sites:
                        if site + ":" in node.value:
                            injected.add(site)
        out: List[Finding] = []
        for site, elt in sites:
            if site not in checked:
                out.append(self.finding(
                    module, elt,
                    f"fault site {site!r} is declared but has no "
                    f"faults.check({site!r}) injection point"))
            elif site not in injected:
                out.append(self.finding(
                    module, elt,
                    f"fault site {site!r} has an injection point but "
                    f"no test injects '{site}:<kind>' — the failure "
                    f"path is untested"))
        return out


# ------------------------------------------------------- schema-coherence

class SchemaCoherenceRule(Rule):
    """Both directions of the report-schema contract: every key a
    section emitter's returned dict literal carries must be schema-
    known (``contracts.SECTION_KEYS`` / ``TOP_KEYS``), and every
    schema-required key must be emitted.  A key someone forgot to
    retire after a schema bump (stale v<=N emission) fails the first
    direction; a schema bump without its emitter fails the second —
    both used to be grep-and-pray."""

    name = "schema-coherence"
    blurb = ("report-section emitters whose dict keys drift from the schema key sets — both directions, stale retired keys included")

    def applies(self, rel: str) -> bool:
        c = _contracts()
        if c is None:
            return False
        return rel in {r for r, _ in c.SECTION_EMITTERS.values()}

    def check(self, project: Project, module: Module) -> List[Finding]:
        c = _contracts()
        if c is None:
            return []
        known = c.schema_keys()
        funcs = {node.name: node for node in module.tree.body
                 if isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
        out: List[Finding] = []
        for section, (_, fname) in sorted(c.SECTION_EMITTERS.items()):
            fn = funcs.get(fname)
            if fn is None:
                continue
            if section == "top":
                emitted = self._top_keys(fn)
            elif section == "dispatch_fetch":
                emitted = self._nested_keys(fn, "dispatch_fetch")
            else:
                emitted = self._return_keys(fn)
            if emitted is None:
                continue
            for key, node in sorted(emitted.items()):
                if key not in known[section]:
                    removed = c.REMOVED_KEYS.get(key)
                    why = (f"retired in schema v{removed[1]}"
                           if removed and removed[0] == section
                           else f"not a schema-v{c.SCHEMA_VERSION} key")
                    out.append(self.finding(
                        module, node,
                        f"`{fname}` emits {section!r} key {key!r} — "
                        f"{why} (racon_tpu/contracts.py)"))
            for key in sorted(known[section] - set(emitted)):
                out.append(self.finding(
                    module, fn,
                    f"schema v{c.SCHEMA_VERSION} requires {section!r} "
                    f"key {key!r} but `{fname}` never emits it"))
        return out

    @staticmethod
    def _dict_keys(d: ast.Dict) -> Dict[str, ast.AST]:
        return {k.value: k for k in d.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)}

    def _return_keys(self, fn) -> Optional[Dict[str, ast.AST]]:
        """Union of string keys over every returned dict literal (None
        when the function never returns one — nothing checkable)."""
        found = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Dict):
                found = {**(found or {}),
                         **self._dict_keys(node.value)}
        return found

    def _report_dict(self, fn) -> Optional[ast.Dict]:
        """build_report's assembled ``rep`` literal — the dict that
        carries "schema_version"."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict) \
                    and "schema_version" in self._dict_keys(node):
                return node
        return None

    def _top_keys(self, fn) -> Optional[Dict[str, ast.AST]]:
        rep = self._report_dict(fn)
        if rep is None:
            return None
        keys = self._dict_keys(rep)
        # conditional sections land via rep["<key>"] = ... assignments
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.slice, ast.Constant) \
                            and isinstance(t.slice.value, str):
                        keys.setdefault(t.slice.value, t)
        return keys

    def _nested_keys(self, fn,
                     section: str) -> Optional[Dict[str, ast.AST]]:
        rep = self._report_dict(fn)
        if rep is None:
            return None
        for k, v in zip(rep.keys, rep.values):
            if isinstance(k, ast.Constant) and k.value == section \
                    and isinstance(v, ast.Dict):
                return self._dict_keys(v)
        return None


# ------------------------------------------------------- state-transition

class StateTransitionRule(Rule):
    """Lifecycle writes must stay inside the declared machines: a
    journal append's ``"rec"`` must be a declared record type, a
    ``job.state = X`` / ``entry["status"] = X`` /
    ``entry.update(status=X)`` target must be a declared state, and a
    write lexically guarded by an equality test of the SAME object's
    state field must encode a declared edge (``collected -> running``
    is a finding).  Unresolvable values and non-equality guards are
    skipped — the rule reports only what it can prove."""

    name = "state-transition"
    blurb = ("journal appends / job+shard state writes minting undeclared states or encoding undeclared lifecycle edges (e.g. `collected->running`)")

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        c = _contracts()
        if c is None:
            return []
        self._c = c
        self._prov = project.provenance()
        self._module = module
        out: List[Finding] = []
        self._visit(module.tree.body, {}, out)
        return out

    # -- machine plumbing ------------------------------------------------

    def _machine(self, kind: str):
        return (self._c.JOB_MACHINE if kind == "job"
                else self._c.SHARD_MACHINE)

    def _field_of(self, expr) -> Optional[Tuple[str, Optional[str]]]:
        """(kind, receiver) when ``expr`` reads a lifecycle field:
        ``<recv>.state`` -> job, ``<recv>["status"]`` /
        ``<recv>.get("status")`` -> shard."""
        if isinstance(expr, ast.Attribute) and expr.attr == "state":
            return "job", dotted(expr.value)
        if isinstance(expr, ast.Subscript) \
                and isinstance(expr.slice, ast.Constant) \
                and expr.slice.value == "status":
            return "shard", dotted(expr.value)
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr == "get" and expr.args \
                and isinstance(expr.args[0], ast.Constant) \
                and expr.args[0].value == "status":
            return "shard", dotted(expr.func.value)
        return None

    def _guards_from_test(self, test) -> Dict[Tuple[str, Optional[str]],
                                              str]:
        """Equality guards a test establishes: {(kind, receiver):
        state}.  Only single ``==`` comparisons bind (an ``in``/``!=``
        narrows nothing usable for one edge)."""
        guards: Dict[Tuple[str, Optional[str]], str] = {}
        tests = (test.values if isinstance(test, ast.BoolOp)
                 and isinstance(test.op, ast.And) else [test])
        for t in tests:
            if not (isinstance(t, ast.Compare) and len(t.ops) == 1
                    and isinstance(t.ops[0], ast.Eq)):
                continue
            for field_expr, value_expr in ((t.left, t.comparators[0]),
                                           (t.comparators[0], t.left)):
                field = self._field_of(field_expr)
                if field is None:
                    continue
                state = self._prov.resolve_str(self._module, value_expr)
                if state is not None:
                    guards[field] = state
        return guards

    # -- statement walk --------------------------------------------------

    _COMPOUND = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                 ast.AsyncWith, ast.Try)

    def _visit(self, stmts, guards, out) -> None:
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self._visit(node.body, {}, out)
                continue
            # simple statements only — a compound statement's nested
            # writes are reached by the recursion below (walking the
            # whole subtree here would double-count them)
            if not isinstance(node, self._COMPOUND):
                self._check_exprs(node, guards, out)
            if isinstance(node, ast.If):
                new = self._guards_from_test(node.test)
                self._visit(node.body, {**guards, **new}, out)
                self._visit(node.orelse, guards, out)
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                self._visit(node.body + node.orelse, guards, out)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                self._visit(node.body, guards, out)
            elif isinstance(node, ast.Try):
                self._visit(node.body, guards, out)
                for h in node.handlers:
                    self._visit(h.body, guards, out)
                self._visit(node.orelse + node.finalbody, guards, out)

    def _check_exprs(self, stmt, guards, out) -> None:
        """Lifecycle writes inside one (simple or header) statement."""
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                field = self._field_of(t)
                if field is not None:
                    self._check_write(field, stmt.value, stmt, guards,
                                      out)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "update":
                for kw in node.keywords:
                    if kw.arg == "status":
                        field = ("shard", dotted(node.func.value))
                        self._check_write(field, kw.value, node,
                                          guards, out)
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        continue
                    if k.value == "rec":
                        rec = self._prov.resolve_str(self._module, v)
                        if rec is not None \
                                and rec not in self._c.JOURNAL_RECORDS:
                            out.append(self.finding(
                                self._module, v,
                                f"journal record type {rec!r} is not "
                                f"declared in contracts."
                                f"JOURNAL_RECORDS"))
                    elif k.value == "status":
                        state = self._prov.resolve_str(self._module, v)
                        if state is not None and \
                                state not in self._c.SHARD_MACHINE:
                            out.append(self.finding(
                                self._module, v,
                                f"shard entry minted with undeclared "
                                f"status {state!r} (contracts."
                                f"SHARD_MACHINE)"))

    def _check_write(self, field, value_expr, node, guards, out) -> None:
        kind, _recv = field
        state = self._prov.resolve_str(self._module, value_expr)
        if state is None:
            return
        machine = self._machine(kind)
        if state not in machine:
            out.append(self.finding(
                self._module, node,
                f"writes undeclared {machine.name} state {state!r} "
                f"(contracts.{machine.name.upper()}_MACHINE states: "
                f"{', '.join(machine.states)})"))
            return
        src = guards.get(field)
        if src is not None and not machine.has_edge(src, state):
            out.append(self.finding(
                self._module, node,
                f"encodes undeclared {machine.name} transition "
                f"{src!r} -> {state!r} — declare the edge in "
                f"racon_tpu/contracts.py or fix the write"))


CONTRACT_RULES = [MetricRegistryRule(), SpanRegistryRule(),
                  FaultSiteRegistryRule(), SchemaCoherenceRule(),
                  StateTransitionRule()]
