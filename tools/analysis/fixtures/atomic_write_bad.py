"""Seeded atomic-write-discipline violations: raw write-mode opens
that can leave a torn artifact for a resume to trust."""

import json


def save_manifest_raw(path, manifest):
    with open(path, "w") as f:         # torn on crash mid-dump
        json.dump(manifest, f)


def append_log(path, line):
    with open(path, "ab") as f:        # raw append, no fsync/rename
        f.write(line)


def spool_result(path, blob):
    with open(path, "wb") as f:        # payload torn on crash
        f.write(blob)
