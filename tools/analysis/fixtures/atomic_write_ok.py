"""Clean twin: the tmp -> fsync -> atomic-rename protocol is
allowlisted, and the one deliberately raw scratch write carries a
reasoned pragma."""

import os


def atomic_write(path, blob):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def scratch(path, blob):
    with open(path, "wb") as f:  # graftlint: disable=atomic-write-discipline (re-derivable scratch file)
        f.write(blob)


class Journal:
    """The fsync'd-append protocol (round 16): a cached append-mode
    handle whose every record is flushed + fsync'd before return."""

    def __init__(self, path):
        self._path = path
        self._f = None

    def _handle(self):
        if self._f is None:
            self._f = open(self._path, "ab")
        return self._f

    def append(self, blob):
        f = self._handle()
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
