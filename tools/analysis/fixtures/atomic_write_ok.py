"""Clean twin: the tmp -> fsync -> atomic-rename protocol is
allowlisted, and the one deliberately raw scratch write carries a
reasoned pragma."""

import os


def atomic_write(path, blob):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def scratch(path, blob):
    with open(path, "wb") as f:  # graftlint: disable=atomic-write-discipline (re-derivable scratch file)
        f.write(blob)
