"""Seeded blocking-under-lock violations: a sleep, a socket send and a
transitively-blocking repo callee, all while a named lock is held."""

import os
import threading
import time


def flush(fd):
    os.fsync(fd)          # makes flush() transitively blocking


class Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None

    def push(self, fd, blob):
        with self._lock:
            time.sleep(0.1)            # sleep under the lock
            self.sock.sendall(blob)    # socket send under the lock
            flush(fd)                  # transitively blocking callee
