"""Clean twin: blocking work happens outside the lock,
``Condition.wait`` (which releases its lock) is exempt, and the one
deliberate hold carries a reasoned pragma."""

import os
import threading
import time


class Writer:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.pending = []

    def push(self, sock, blob):
        with self._lock:
            self.pending.append(blob)
        sock.sendall(blob)
        time.sleep(0)

    def wait_ready(self):
        with self._cond:
            self._cond.wait(0.1)

    def checkpoint(self, fd):
        with self._lock:
            # graftlint: disable=blocking-under-lock (the lock exists to serialize the checkpoint write)
            os.fsync(fd)
