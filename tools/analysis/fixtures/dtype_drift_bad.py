"""Seeded dtype-drift violations (expect 3): int16/uint16 SWAR lanes
silently promoted to int32 across an op boundary."""
import jax.numpy as jnp


def mix_binop(x):
    lanes = x.astype(jnp.int16)
    wide = jnp.arange(8)              # int32 by default
    # BAD: silent int16 -> int32 promotion in arithmetic
    return lanes + wide


def mix_where(mask, x, y):
    a = x.astype(jnp.uint16)
    b = y.astype(jnp.int32)
    # BAD: the uint16 lane silently widens to match b
    return jnp.where(mask, a, b)


def mix_minimum(x):
    a = jnp.zeros((4,), dtype=jnp.int16)
    b = jnp.zeros((4,), dtype=jnp.int32)
    # BAD: min over mixed widths promotes the packed lane
    return jnp.minimum(a, b)
