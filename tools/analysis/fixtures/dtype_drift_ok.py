"""Clean twin of dtype_drift_bad (expect 0 reported, 1 suppressed):
explicit widening, all-narrow arithmetic, and a reasoned pragma on a
deliberate accumulator boundary."""
import jax.numpy as jnp


def explicit_widen(x):
    lanes = x.astype(jnp.int16)
    wide = jnp.arange(8)
    return lanes.astype(jnp.int32) + wide


def stays_narrow(x, y):
    a = x.astype(jnp.int16)
    b = y.astype(jnp.int16)
    return jnp.minimum(a, b)


def deliberate_boundary(x):
    votes = x.astype(jnp.uint16)
    acc = jnp.zeros((8,), dtype=jnp.int32)
    # graftlint: disable=dtype-drift (accumulator boundary: the promotion to int32 is the point)
    return acc + votes
