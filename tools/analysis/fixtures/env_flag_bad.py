"""Seeded env-flag-registry violations (graftlint selftest fixture)."""
import os

from racon_tpu import flags


def bad_direct():
    return os.environ.get("RACON_TPU_FIXTURE_DIRECT", "")   # VIOLATION


def bad_subscript():
    return os.environ["RACON_TPU_FIXTURE_SUB"]              # VIOLATION


def bad_undeclared():
    return flags.get_bool("RACON_TPU_FIXTURE_UNDECLARED")   # VIOLATION
