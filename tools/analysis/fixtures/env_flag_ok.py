"""Clean twin of env_flag_bad.py — zero findings expected."""
import os

from racon_tpu import flags


def ok_registry():
    return flags.get_bool("RACON_TPU_SWAR")     # ok: declared flag


def ok_other_namespace():
    return os.environ.get("XLA_FLAGS", "")      # ok: not RACON_TPU_*


def ok_write(value):
    os.environ["RACON_TPU_SWAR"] = value        # ok: writes (test toggles)
