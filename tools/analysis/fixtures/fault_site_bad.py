"""Seeded fault-site-registry violations.  Site ``fixture.alpha`` is
fully wired (check call + injection spec below); ``fixture.beta`` and
``fixture.delta`` have no faults.check injection point at all;
``fixture.gamma`` has a check call but no test injects it."""

FAULT_SITES = (
    "fixture.alpha",
    "fixture.beta",
    "fixture.gamma",
    "fixture.delta",
)


def hot_path(faults):
    faults.check("fixture.alpha")
    faults.check("fixture.gamma")


def test_alpha_injection(monkeypatch):
    monkeypatch.setenv("RACON_TPU_FAULTS", "fixture.alpha:io_error")
