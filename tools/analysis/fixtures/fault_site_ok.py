"""Clean twin: ``fixture.sigma`` carries both halves of the chaos
contract; ``fixture.tau`` is checked but intentionally exercised by a
direct monkeypatch rather than an env spec, so its element carries a
reasoned pragma."""

FAULT_SITES = (
    "fixture.sigma",
    "fixture.tau",  # graftlint: disable=fault-site-registry (exercised via direct monkeypatch of the check hook, not an env spec)
)


def hot_path(faults):
    faults.check("fixture.sigma")
    faults.check("fixture.tau")


def test_sigma_injection(monkeypatch):
    monkeypatch.setenv("RACON_TPU_FAULTS", "fixture.sigma:stall")
