"""Seeded host-sync-in-hot-loop violations (graftlint selftest
fixture). Pretends to live in racon_tpu/ — the selftest runs unscoped."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def kernel(x):
    return x + 1


def hot_loop(chunks):
    outs = []
    for c in chunks:
        out = kernel(c)
        outs.append(np.asarray(out))        # VIOLATION: pull per chunk
        out.block_until_ready()             # VIOLATION: sync per chunk
        s = int(out)                        # VIOLATION: hidden sync
    return outs, s


def hot_loop2(chunks):
    res = []
    for c in chunks:
        res.append(jax.device_get(c))       # VIOLATION: per-item fetch
    return res
