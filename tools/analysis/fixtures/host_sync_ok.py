"""Clean twin of host_sync_bad.py — zero findings expected."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def kernel(x):
    return x + 1


def fetch_global(tree):
    return jax.device_get(tree)             # ok: sanctioned primitive


def pipelined(chunks):
    inflight = [kernel(c) for c in chunks]
    outs = []
    for out in inflight:
        host = fetch_global([out])          # ok: one sanctioned fetch
        outs.append(np.asarray(host[0]))    # ok: already host-side
    return outs


def outside_loop(c):
    out = kernel(c)
    return np.asarray(out)                  # ok: not inside a loop
