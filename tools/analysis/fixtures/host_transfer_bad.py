"""Seeded host-transfer-in-jit violations (expect 3): implicit
np.asarray/np.* on traced values inside jit-reachable functions —
directly and through an interprocedural call."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def kernel(x, *, k):
    y = jnp.cumsum(x)
    # BAD: np on a tracer — implicit host transfer at trace time
    host = np.asarray(y)
    # BAD: np reduction of a traced value
    peak = np.max(y)
    return x + host[0] + peak + k


def helper(v):
    # BAD: reached with a traced argument from kernel2
    return np.ascontiguousarray(v)


@jax.jit
def kernel2(x):
    return helper(x * 2)
