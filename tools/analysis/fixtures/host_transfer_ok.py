"""Clean twin of host_transfer_bad (expect 0 reported, 1 suppressed):
np on static values inside jit, sanctioned host-side fetches outside
it, and a reasoned pragma on an interpret-mode probe."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def kernel(x, *, k):
    # np over a STATIC argument builds a compile-time table — fine
    table = np.arange(k)
    return x + jnp.asarray(table)[0]


def fetch(out):
    # host-side fetch after dispatch: not jit-reachable, not flagged
    return np.asarray(out)


@jax.jit
def probe(x):
    # graftlint: disable=host-transfer-in-jit (interpret-mode identity probe)
    return np.asarray(x)
