"""Seeded jit-in-loop violations (expect 3): jit wrappers constructed
per loop iteration — every iteration recompiles into an empty cache."""
import jax


def per_iteration(fns, xs):
    out = []
    for f, x in zip(fns, xs):
        # BAD: fresh jit wrapper (and cache) per iteration
        jf = jax.jit(f)
        out.append(jf(x))
    return out


def nested_def(xs):
    res = []
    for x in xs:
        # BAD: a jit-decorated def per iteration
        @jax.jit
        def step(v):
            return v * 2

        res.append(step(x))
    return res


def while_retrace(x):
    k = 0
    while k < 3:
        # BAD: the closure over k builds a new wrapper each pass
        x = jax.jit(lambda v: v + k)(x)
        k += 1
    return x
