"""Clean twin of jit_in_loop_bad (expect 0 reported, 1 suppressed):
hoisted jit callables called from loops, plus a reasoned pragma on a
deliberate compile-behaviour probe."""
import jax


@jax.jit
def step(v):
    return v * 2


def hoisted(xs):
    return [step(x) for x in xs]


def loop_calls(xs):
    out = []
    for x in xs:
        out.append(step(x))
    return out


def probe(xs):
    for x in xs:
        # graftlint: disable=jit-in-loop (compile-behaviour probe: single iteration by construction)
        f = jax.jit(lambda v: v)
        return f(x)
