"""Seeded jit-shape-hazard violations (expect 3): unbounded values
reaching shape-determining parameters of a jit root — directly and
through a forwarding function."""
import functools
import time

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("max_len", "band"))
def kernel(x, *, max_len, band):
    pad = jnp.zeros((max_len + band,), jnp.int32)
    return x + pad[0]


@functools.partial(jax.jit, static_argnames=("scale",))
def scaled(x, *, scale):
    return x * jnp.full((4,), scale)


def launch(x, max_len, band):
    # forwards into the kernel statics: shape-determining by propagation
    return kernel(x, max_len=max_len, band=band)


def drive_raw_len(x, pairs):
    # BAD: len() of a runtime list reaches max_len through launch()
    return launch(x, len(pairs), 64)


def drive_unquantized(x, pairs):
    total = sum(len(p) for p in pairs)
    # BAD: un-quantized aggregate reaches the kernel's static directly
    return kernel(x, max_len=total, band=64)


def drive_clock(x):
    # BAD: a per-call varying value as a compiled static
    return scaled(x, scale=int(time.monotonic()))
