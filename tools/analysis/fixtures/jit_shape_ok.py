"""Clean twin of jit_shape_bad (expect 0 reported, 1 suppressed):
geometry quantized through pow2/bucket helpers, module constants and a
reasoned pragma for the deliberate exception."""
import functools

import jax
import jax.numpy as jnp

BUCKET_BAND = 512


def _pow2_at_least(x):
    p = 1
    while p < max(1, x):
        p *= 2
    return p


@functools.partial(jax.jit, static_argnames=("max_len", "band"))
def kernel(x, *, max_len, band):
    pad = jnp.zeros((max_len + band,), jnp.int32)
    return x + pad[0]


def launch(x, max_len, band):
    return kernel(x, max_len=max_len, band=band)


def drive_quantized(x, pairs):
    B = _pow2_at_least(len(pairs))
    return launch(x, B, BUCKET_BAND)


def drive_constant(x):
    return kernel(x, max_len=256, band=BUCKET_BAND)


def drive_probe(x, pairs):
    # graftlint: disable=jit-shape-hazard (availability probe: runs once per process)
    return kernel(x, max_len=len(pairs), band=64)
