"""Seeded lock-discipline violations: shared attributes written from a
thread root and the main path with no common guarding lock."""

import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = 0
        self.results = {}

    def _worker(self):
        self.jobs += 1              # unguarded thread-side write
        with self._lock:
            self.results["x"] = 1   # guarded here...

    def start(self):
        t = threading.Thread(target=self._worker, daemon=True)
        t.start()
        self.jobs -= 1              # unguarded main-path write
        self.results["y"] = 2       # ...unguarded there: no common lock
        t.join()
