"""Clean twin: every cross-context write shares one lock (the
Condition aliases to it), and the single deliberate exception carries a
reasoned pragma."""

import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.jobs = 0
        self.last_beat = 0.0

    def _worker(self):
        with self._cond:
            self.jobs += 1
        # graftlint: disable=lock-discipline (monotonic float beat: a torn read only delays the watchdog)
        self.last_beat = 1.0

    def start(self):
        t = threading.Thread(target=self._worker, daemon=True)
        t.start()
        with self._lock:
            self.jobs -= 1
        self.last_beat = 2.0
        t.join()
