"""Seeded metric-registry violations: a name that breaks the grammar,
an unregistered static name, and a dynamic name under an unregistered
prefix."""


def emit(metrics, dev):
    metrics.inc("Bad-Name")                         # grammar violation
    metrics.set_gauge("totally.unregistered_metric", 1)   # not in METRICS
    metrics.add_time(f"unknownpfx.{dev}.t_s", 0.5)  # unregistered prefix
