"""Clean twin: registered static names (direct and via a module
constant), a dynamic name under a registered prefix, an unresolvable
name (skipped, not guessed), and one pragma'd intentional stray."""

_CHUNKS = "align.chunks"


def emit(metrics, dev, name):
    metrics.inc(_CHUNKS)
    metrics.set_gauge("queue.depth", 3)
    metrics.add_time("queue.consumer_wait_s", 0.1)
    metrics.inc(f"device.{dev}.fetches")
    metrics.inc(name)  # unresolvable -> skipped
    metrics.inc("not.registered.here")  # graftlint: disable=metric-registry (scratch counter for a local perf probe, never reported)
