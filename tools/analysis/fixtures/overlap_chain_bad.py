"""Seeded overlapper-shaped jit-shape-hazard violations (expect 3):
unbounded seed/pair counts reaching the chain kernel's static arena
geometry — raw ``len()`` through a forwarding launcher, an un-quantized
hit aggregate, and a per-call varying static."""
import functools
import time

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("S", "B"))
def chain_kernel(ts, *, S, B):
    arena = jnp.zeros((B, S), jnp.int32)
    return ts + arena[0, 0]


@functools.partial(jax.jit, static_argnames=("k",))
def score_kernel(ts, *, k):
    return ts * jnp.full((4,), k)


def launch(ts, S, B):
    # forwards into the chain kernel statics: shape-determining by
    # propagation
    return chain_kernel(ts, S=S, B=B)


def drive_raw_pair_count(ts, pairs):
    # BAD: len() of the runtime candidate-pair list reaches the arena
    # batch dimension through launch()
    return launch(ts, 32, len(pairs))


def drive_unquantized_seeds(ts, hits):
    total = sum(len(h) for h in hits)
    # BAD: un-quantized seed aggregate becomes the lane width directly
    return chain_kernel(ts, S=total, B=16)


def drive_clock_k(ts):
    # BAD: a per-call varying value as a compiled static
    return score_kernel(ts, k=int(time.monotonic()))
