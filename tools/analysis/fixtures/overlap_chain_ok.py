"""Clean overlapper-shaped warmup-coverage twin (expect 0 reported, 1
suppressed): the seed-bucket and pair-batch quantizers are shared
between ``_warmup_shapes`` and the dispatch path, with a reasoned
pragma on the data-dependent hot-bucket escalation."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("S", "B"))
def chain_kernel(ts, *, S, B):
    arena = jnp.zeros((B, S), jnp.int32)
    return ts + arena[0, 0]


def _seed_bucket(n):
    """THE pow2 lane-width rule — dispatch and warm-up both call it."""
    b = 16
    while b < n:
        b *= 2
    return b


def _pair_batch(S, n):
    """THE arena batch rule (cells-bounded) — shared on both sides."""
    cap = max(1, (1 << 21) // S)
    b = 1
    while b < n and b < cap:
        b *= 2
    return b


def _escalation_bucket(n):
    """Hot-bucket escalation geometry: deliberately uncovered
    (data-dependent and rare by design)."""
    b = 64
    while b < n:
        b *= 2
    return b


class ChainEngine:
    def _warmup_shapes(self, est_seeds, est_pairs):
        S = _seed_bucket(est_seeds)
        return [(S, _pair_batch(S, est_pairs))]

    def dispatch(self, ts, pairs):
        S = _seed_bucket(max(len(p) for p in pairs))
        B = _pair_batch(S, len(pairs))
        return chain_kernel(ts, S=S, B=B)

    def escalate(self, ts, hot_pairs):
        # graftlint: disable=warmup-coverage (hot-bucket escalation shapes are data-dependent and rare by design)
        S = _escalation_bucket(2 * max(len(p) for p in hot_pairs))
        return chain_kernel(ts, S=S, B=1)
