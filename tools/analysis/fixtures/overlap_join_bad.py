"""Seeded device-join-shaped host-transfer-in-jit violations (expect
3): np.* on traced join intermediates inside the jit'd sort/expand
kernels — the exact transfers the round-21 device seed join exists to
eliminate — directly and through the interprocedural ramp helper."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("max_occ",))
def join_sort_kernel(rh, th, *, max_occ):
    cnt = (jnp.searchsorted(th, rh, side="right")
           - jnp.searchsorted(th, rh, side="left"))
    # BAD: np reduction of the traced per-seed hit counts — concretizes
    # one batch's join cardinality into the compiled program
    total = np.sum(cnt)
    # BAD: np.asarray of the traced offset vector (implicit transfer)
    offs = np.asarray(jnp.cumsum(cnt))
    return cnt + total + offs[0] + max_occ


def _ramp(e, offs):
    # BAD: reached with traced arguments from join_expand_kernel
    return np.searchsorted(offs, e, side="right")


@jax.jit
def join_expand_kernel(offs):
    e = jnp.arange(offs.shape[0])
    return _ramp(e, offs)
