"""Clean device-join twin (expect 0 host-sync-in-hot-loop reported, 1
suppressed): the double-buffered chain-chunk pipeline fetches through
the sanctioned ``fetch_global`` primitive only when the in-flight
budget forces it, with a reasoned pragma on the one deliberate
per-chunk sync (the arena-overflow probe)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def chain_kernel(ts):
    return jnp.cumsum(ts, axis=-1)


def fetch_global(tree):
    return jax.device_get(tree)             # ok: sanctioned primitive


def pipelined_chunks(chunks, budget=2):
    inflight = []
    rows = []
    for c in chunks:
        inflight.append(chain_kernel(c))
        while len(inflight) > budget:
            host = fetch_global([inflight.pop(0)])  # ok: sanctioned
            rows.append(np.asarray(host[0]))        # ok: host-side
    for out in inflight:
        rows.append(fetch_global([out])[0])         # ok: sanctioned
    return rows


def overflow_probe(chunks):
    for c in chunks:
        out = chain_kernel(c)
        # graftlint: disable=host-sync-in-hot-loop (arena-overflow probe: one deliberate sync per chunk gates the bail-out ladder)
        out.block_until_ready()
    return True
