"""Pragma-hygiene fixture: unknown rule names are findings."""

x = 1  # graftlint: disable=not-a-rule (typo'd pragma suppresses nothing)
