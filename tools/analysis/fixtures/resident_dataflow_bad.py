"""Seeded host-transfer-in-jit violations on the fused resident
align->consensus dataflow shape (expect 3): a jit'd row-derive root
that round-trips its packed breaking-point table through numpy
mid-derive, and a lane-gather helper reached with a traced pool from a
second jit root — exactly the mid-pipeline transfers the resident
dataflow exists to eliminate."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("nw",))
def derive_rows(bp_first, bp_last, *, nw):
    span = (bp_last & 0x3FFF) - (bp_first & 0x3FFF) + 1
    # BAD: np reduction of the traced span table — host transfer in
    # the middle of the fused derive
    widest = np.max(span)
    # BAD: np.asarray on the traced packed table (numpy __array__
    # concretizes one batch's breaking points into the program)
    host_rows = np.asarray(bp_first >> 14)
    return span + widest + host_rows[0] + nw


def gather_lanes(pool, rows):
    # BAD: reached with traced (pool, rows) from consensus_root — the
    # lane gather must stay on device, not bounce through numpy
    return np.take(pool, rows)


@jax.jit
def consensus_root(pool, rows):
    return gather_lanes(pool * 1, jnp.clip(rows, 0, pool.shape[0] - 1))
