"""Clean twin of resident_dataflow_bad (expect 0 reported, 1
suppressed): the fused derive/gather roots compute with jnp end to
end, the only numpy touches are a compile-time static table and the
sanctioned post-dispatch fetch, and the deliberate gate-scalar fetch
carries a reasoned pragma."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("nw",))
def derive_rows(bp_first, bp_last, *, nw):
    # np over the STATIC window count builds a compile-time ramp — fine
    ramp = np.arange(nw)
    span = (bp_last & 0x3FFF) - (bp_first & 0x3FFF) + 1
    return span + jnp.max(span) + jnp.asarray(ramp)[0]


@jax.jit
def consensus_root(pool, rows):
    return jnp.take(pool, jnp.clip(rows, 0, pool.shape[0] - 1))


def fetch_rows(out):
    # host-side fetch after dispatch: not jit-reachable, not flagged
    return np.asarray(out)


@jax.jit
def gate_probe(score):
    # graftlint: disable=host-transfer-in-jit (12 B/lane gate-scalar fetch probe runs in interpret mode only)
    return np.asarray(score)
