"""Seeded schema-coherence violations: ``queue_summary`` emits an
unknown key and drops a required one; ``dataflow_summary`` drops a
required key."""


def queue_summary():
    return {
        "depth": 1,
        "producer_wait_s": 0.0,
        "consumer_wait_s": 0.0,
        "bogus_key": 9,
    }


def dataflow_summary():
    return {
        "resident": True,
        "bytes_fetched": 0,
        "bytes_avoided": 0,
        "fallback_pairs": 0,
        "ins_overflow_windows": 0,
        "lanes_device_groups": 0,
    }
