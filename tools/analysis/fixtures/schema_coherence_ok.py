"""Clean twin: a faithful ``queue_summary`` plus one pragma'd local
probe key that is stripped before report assembly."""


def queue_summary():
    return {
        "depth": 1,
        "producer_wait_s": 0.0,
        "consumer_wait_s": 0.0,
        "stall_s": 0.0,
        "debug_probe": 1,  # graftlint: disable=schema-coherence (local debug probe, stripped before report assembly)
    }
