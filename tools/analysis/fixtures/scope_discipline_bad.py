"""Seeded scope-discipline violations: hand-built ``job.`` metric
names bypass the thread-local scoping that keeps concurrent service
jobs' metrics disjoint."""

from racon_tpu.obs import metrics


def publish(job_id, n):
    metrics.set_scope(f"job.{job_id}.")
    metrics.inc("job.7.windows", n)
    metrics.clear("job.7.")
