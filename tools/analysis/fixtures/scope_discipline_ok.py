"""Clean twin: the job namespace only via job_scope/clear_job (reads
through a scope variable are always fine), plus one pragma'd negative
probe."""

from racon_tpu.obs import metrics


def publish(job_id, n):
    scope = metrics.job_scope(job_id)
    metrics.set_scope(scope)
    metrics.inc("windows", n)
    metrics.set_scope(None)
    metrics.clear_job(job_id)


def probe():
    # graftlint: disable=scope-discipline (negative probe: asserts the registry rejects hand-built scopes)
    metrics.set_gauge("job.0.probe", 1)
