"""Seeded span-discipline violations (selftest expects 3)."""

from racon_tpu import obs


def work():
    pass


def leak_via_assignment():
    s = obs.span("align.dispatch")  # finding: held by hand
    s.__enter__()
    work()
    s.__exit__(None, None, None)


def leak_via_manual_enter():
    obs.span("poa.fetch").__enter__()  # finding: manual begin, no end
    work()


def leak_via_helper(run_under):
    run_under(obs.span("exec.shard"))  # finding: span escapes the frame
