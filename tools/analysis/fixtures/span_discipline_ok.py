"""Clean span usage (selftest expects 0 reported, 1 pragma-suppressed)."""

from racon_tpu import obs


def work(arg=None):
    pass


def good_plain():
    with obs.span("align.dispatch"):
        work()


def good_as_and_args():
    with obs.span("poa.pack", windows=3) as sp:
        work(sp)


def good_multi_item():
    with obs.span("consensus"), obs.span("queue.get"):
        work()


def deliberate_identity_probe():
    # the disabled-span fast path returns one shared singleton; probing
    # it is the one sanctioned non-with use
    probe = obs.span("x")  # graftlint: disable=span-discipline (identity probe of the disabled-path singleton, never entered)
    work(probe)
