"""Seeded span-registry violations: three span names not declared in
contracts.SPANS — each would orphan the report's span-timer reads."""


def work(obs, trace, span):
    with obs.span("totally.unknown"):
        pass
    with span("made.up.name"):
        pass
    with trace.span("renamed.silently"):
        pass
