"""Clean twin: declared span names (direct and via a module constant),
an unresolvable name (skipped), and one pragma'd experimental span."""

_FETCH = "align.fetch"


def work(obs, span, dynamic):
    with obs.span("align.dispatch"):
        pass
    with span(_FETCH):
        pass
    with obs.span(dynamic):  # unresolvable -> skipped
        pass
    with obs.span("scratch.probe"):  # graftlint: disable=span-registry (ad-hoc profiling span, timer never read by the report)
        pass
