"""Seeded state-transition violations: an undeclared job edge
(collected -> running), an undeclared job state, and an unknown
journal record type."""


def resurrect(job):
    if job.state == "collected":
        job.state = "running"


def corrupt(job):
    job.state = "zombie"


def replay(journal, job_id):
    journal.append({"rec": "resubmitted", "id": job_id})
