"""Clean twin: declared shard requeue and promote edges, an unguarded
declared job write, a declared journal record, and one pragma'd
experimental state."""


def requeue(entry):
    if entry["status"] == "done":
        entry["status"] = "pending"


def promote(entry):
    if entry.get("status") == "pending":
        entry.update(status="running")


def schedule(job, journal):
    job.state = "queued"
    journal.append({"rec": "done", "id": 1})


def pause(job):
    job.state = "paused"  # graftlint: disable=state-transition (experimental pause state, round-23 lifecycle candidate)
