"""Seeded swallowed-exception violations (graftlint selftest fixture)."""


def swallow_pass():
    try:
        risky()
    except Exception:               # VIOLATION: silent pass
        pass


def swallow_bare():
    try:
        risky()
    except:                         # VIOLATION: bare except, silent return
        return None


def pragma_without_reason():
    try:
        risky()
    except Exception:  # graftlint: disable=swallowed-exception
        pass            # VIOLATION: pragma must carry a (reason)


def swallow_behind_dead_callback():
    import warnings

    try:
        risky()
    except Exception as e:          # VIOLATION: log lives in a nested
        def report():               # def that is never called
            warnings.warn(str(e))
