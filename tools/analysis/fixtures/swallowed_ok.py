"""Clean twin of swallowed_bad.py — zero reported findings expected
(one finding is pragma-suppressed)."""
import sys
import warnings


def reraise():
    try:
        risky()
    except Exception:
        raise


def logs_directly():
    try:
        risky()
    except Exception as e:
        warnings.warn(f"swallowed: {e}")


def my_logger(msg):
    print(msg, file=sys.stderr)


def logs_transitively():
    try:
        risky()
    except Exception as e:
        my_logger(str(e))


def narrow():
    try:
        risky()
    except ValueError:              # ok: narrow handler, out of scope
        return None


def pragma_with_reason():
    try:
        risky()
    except Exception:  # graftlint: disable=swallowed-exception (fixture demo)
        pass
