"""Seeded swar-guard violations (graftlint selftest fixture)."""


def kern(x, *, swar=False):
    return x


def kern2(x, use_swar=False):
    return x


def caller_literal(x):
    return kern(x, swar=True)       # VIOLATION: unguarded literal on


def caller_unguarded(x, want):
    use = bool(want)                # not derived from swar_fits/swar_ok
    return kern(x, swar=use)        # VIOLATION


def caller_positional(x):
    return kern2(x, True)           # VIOLATION: positional literal on
