"""Clean twin of swar_guard_bad.py — zero findings expected."""


def swar_fits(n):
    return n < 16384


def kern(x, *, use_swar=False):
    return x


def forward(x, use_swar=False):
    return kern(x, use_swar=use_swar)   # ok: conventional forwarding


def caller(x, n):
    sw = swar_fits(n)
    return kern(x, use_swar=sw)         # ok: guard-derived

def caller_chained(x, n, want):
    sw = want and swar_fits(n)
    sw2 = sw and n % 2 == 0
    return kern(x, use_swar=sw2)        # ok: guard-derived through sw


def caller_off(x):
    return kern(x, use_swar=False)      # ok: literal off-switch

def caller_pragma(x):
    # graftlint: disable=swar-guard (fixture: geometry fits by construction)
    return kern(x, use_swar=True)
