"""Seeded thread-lifecycle violations: fire-and-forget threads with no
join and no stop-event wiring."""

import threading


def _poll_forever():
    while True:
        pass


def leak_module_thread():
    threading.Thread(target=_poll_forever).start()


class Daemon:
    def _run(self):
        while True:
            pass

    def spawn(self):
        t = threading.Thread(target=self._run, daemon=True)
        t.start()


class Pool:
    """The depth-1 wiring check must not go blind: an entry whose
    helper does NOT poll a stop event is still a leak."""

    def _helper(self):
        while True:
            pass

    def _loop(self):
        self._helper()

    def spawn(self):
        threading.Thread(target=self._loop, daemon=True).start()
