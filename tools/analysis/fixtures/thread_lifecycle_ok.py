"""Clean twin: a stop-event-wired daemon that is also joined, and one
deliberately abandoned helper with a reasoned pragma."""

import threading


class Worker:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def _tick(self):
        while not self._stop.wait(0.1):
            pass

    def start(self):
        self._thread = threading.Thread(target=self._tick, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join()


class Igniter:
    def launch(self, fn):
        # graftlint: disable=thread-lifecycle (droppable best-effort helper; daemon dies harmlessly at exit)
        threading.Thread(target=fn, daemon=True).start()


class Supervisor:
    """The supervisor-restartable worker shape (round 16): the entry
    loops forever, but its scheduling helper polls the stop event —
    wired one call level deep, no join needed (the supervisor respawns
    the thread on death, so a class-wide join cannot exist)."""

    def __init__(self):
        self._stop = threading.Event()

    def _next(self):
        while not self._stop.is_set():
            return object()
        return None

    def _worker_loop(self):
        while True:
            job = self._next()
            if job is None:
                return

    def respawn(self):
        threading.Thread(target=self._worker_loop,
                         daemon=True).start()
