"""Clean twin: a stop-event-wired daemon that is also joined, and one
deliberately abandoned helper with a reasoned pragma."""

import threading


class Worker:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None

    def _tick(self):
        while not self._stop.wait(0.1):
            pass

    def start(self):
        self._thread = threading.Thread(target=self._tick, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join()


class Igniter:
    def launch(self, fn):
        # graftlint: disable=thread-lifecycle (droppable best-effort helper; daemon dies harmlessly at exit)
        threading.Thread(target=fn, daemon=True).start()
