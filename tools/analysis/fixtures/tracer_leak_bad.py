"""Seeded tracer-leak violations (graftlint selftest fixture — parsed,
never imported)."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("n",))
def kernel(x, y, *, n):
    if x > 0:                       # VIOLATION: python if on a tracer
        y = y + 1
    k = int(x)                      # VIOLATION: int() on a tracer
    z = x + y
    while z.sum() > 0:              # VIOLATION: while on a derived tracer
        z = z - 1
    v = x.item()                    # VIOLATION: .item() on a tracer
    for i in range(n):              # ok: n is static
        z = z + i
    return z, k, v


def helper(a, b):
    if a > b:                       # VIOLATION: reached from kernel2
        return a
    return b


@jax.jit
def kernel2(x):
    return helper(x, x + 1)
