"""Clean twin of tracer_leak_bad.py — zero findings expected."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("n", "flag"))
def kernel(x, *, n, flag):
    if flag:                        # ok: static argument
        x = x + 1
    y = jnp.where(x > 0, x, -x)     # ok: traced select, no python branch
    B = x.shape[0]                  # ok: shape reads are static
    for i in range(n):              # ok: static trip count
        y = y + i
    if B > 4:                       # ok: branching on a static shape
        y = y * 2
    return y


def host(paths):
    if len(paths) > 0:              # ok: host-only, not jit-reachable
        return int(paths[0])
    return 0
