"""Seeded warmup-coverage violations (expect 3): a dispatch-path
geometry helper _warmup_shapes never calls, plus inline pow2
quantization loops on both the dispatch and warm-up sides."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("max_len",))
def _kernel(x, *, max_len):
    return x + jnp.zeros((max_len,), jnp.int32)[0]


def _dispatch_cap(n):
    """A geometry quantizer only the dispatch path uses."""
    c = 64
    while c < n:
        c *= 2
    return c


class Engine:
    def _warmup_shapes(self, est):
        # BAD: inline pow2 loop on the warm-up side — parallel
        # re-implementation of the dispatch derivation
        B = 1
        while B < est:
            B *= 2
        return [(B,)]

    def dispatch(self, x, items):
        # BAD: helper not (transitively) called by _warmup_shapes
        max_len = _dispatch_cap(len(items))
        # BAD: inline pow2 loop on the dispatch path
        B = 1
        while B < len(items):
            B *= 2
        return _kernel(x, max_len=max_len)
