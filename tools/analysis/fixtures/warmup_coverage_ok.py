"""Clean twin of warmup_coverage_bad (expect 0 reported, 1
suppressed): one shared geometry helper on both sides, and a reasoned
pragma on the data-dependent escalation derivation."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("max_len",))
def _kernel(x, *, max_len):
    return x + jnp.zeros((max_len,), jnp.int32)[0]


def _shared_cap(n):
    """THE pow2 rule — dispatch and warm-up both call it."""
    c = 64
    while c < n:
        c *= 2
    return c


def _escape_cap(n):
    """Escalation geometry: deliberately uncovered (data-dependent)."""
    c = 128
    while c < n:
        c *= 2
    return c


class Engine:
    def _warmup_shapes(self, est):
        return [(_shared_cap(est),)]

    def dispatch(self, x, items):
        max_len = _shared_cap(len(items))
        return _kernel(x, max_len=max_len)

    def escalate(self, x, items):
        # graftlint: disable=warmup-coverage (escalation shapes are data-dependent and rare by design)
        max_len = _escape_cap(2 * len(items))
        return _kernel(x, max_len=max_len)
