"""The graftlint rule set.

Each rule is a callable object: ``rule.check(project, module) ->
[Finding]``; the driver applies path scoping (``rule.applies(rel)``)
and pragma suppression.  Rules are repo-aware — they consult the
project-wide function index, jit-reachability/taint, and the logging
closure built in :mod:`tools.analysis.astutil`.

| rule                  | catches                                        |
| --------------------- | ---------------------------------------------- |
| tracer-leak           | Python control flow / int() / bool() / .item() |
|                       | on traced values in jit-reachable kernels      |
| swar-guard            | packed int16 entry points not dominated by a   |
|                       | swar_fits-family overflow guard                |
| swallowed-exception   | except Exception that neither re-raises nor    |
|                       | logs (directly or via a repo logging function) |
| env-flag-registry     | RACON_TPU_* env reads outside racon_tpu/flags  |
|                       | and reads of undeclared flag names             |
| host-sync-in-hot-loop | device->host pulls / block_until_ready inside  |
|                       | the per-chunk loops of the engines             |
| span-discipline       | obs.span(...) used any way other than directly |
|                       | as a `with` item (manual spans leak open)      |

The concurrency & durability pack (round 15) lives in
:mod:`tools.analysis.concurrency` and registers below: lock-discipline,
blocking-under-lock, atomic-write-discipline, thread-lifecycle and
scope-discipline.  The compile-surface pack (round 18) lives in
:mod:`tools.analysis.compilesurface` and registers below too:
jit-shape-hazard, dtype-drift, jit-in-loop, warmup-coverage and
host-transfer-in-jit.  The contract pack (round 22) lives in
:mod:`tools.analysis.contracts`: metric-registry, span-registry,
fault-site-registry, schema-coherence and state-transition —
21 rules total.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import List, Optional, Set

from .astutil import (Module, Project, dotted, iter_own_calls,
                      iter_own_nodes, last_segment, map_call_args)


@dataclass
class Finding:
    rule: str
    rel: str
    line: int
    message: str
    # pragma state, filled by the driver: None = no pragma applied;
    # a string = the reason of the pragma that suppressed this finding
    pragma: Optional[str] = None

    def __str__(self) -> str:
        return f"{self.rel}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        """The ``--json`` record: rule, path, line, message, pragma
        state."""
        return {"rule": self.rule, "path": self.rel, "line": self.line,
                "message": self.message, "pragma": self.pragma}


class Rule:
    name = "?"

    def applies(self, rel: str) -> bool:
        return rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.name, module.rel,
                       getattr(node, "lineno", 1), message)


# ------------------------------------------------------------ tracer-leak

class TracerLeakRule(Rule):
    """Python-level branching or concretization of traced values inside
    jit-reachable functions: ``if``/``while``/``for``/``assert`` on a
    traced expression, ``int()``/``bool()``/``float()`` of a traced
    value, ``.item()``/``.tolist()`` on a traced value. All of these
    either fail at trace time on real tracers or — worse — silently
    bake one traced batch's concrete value into the compiled program."""

    name = "tracer-leak"
    blurb = ("Python control flow / `int()` / `.item()` on traced values in jit-reachable kernels")
    CASTS = {"int", "bool", "float", "complex"}
    PULL_METHODS = {"item", "tolist"}

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/ops/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        taints = project.taints()
        for fi in project.functions:
            if fi.module is not module or id(fi) not in taints:
                continue
            tainted = taints[id(fi)]
            for node in iter_own_nodes(fi.node):
                out.extend(self._check_node(project, module, fi.qualname,
                                            node, tainted))
        return out

    def _check_node(self, project, module, qual, node, tainted):
        t = lambda e: project.expr_tainted(e, tainted)
        if isinstance(node, (ast.If, ast.While)) and t(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            yield self.finding(
                module, node,
                f"Python `{kind}` on a traced value in jit-reachable "
                f"`{qual}` — use jnp.where/lax.cond (or mark the "
                f"argument static)")
        elif isinstance(node, ast.IfExp) and t(node.test):
            yield self.finding(
                module, node,
                f"conditional expression on a traced value in "
                f"jit-reachable `{qual}` — use jnp.where")
        elif isinstance(node, (ast.For, ast.AsyncFor)) and t(node.iter):
            yield self.finding(
                module, node,
                f"Python `for` over a traced value in jit-reachable "
                f"`{qual}` — use lax.scan/fori_loop")
        elif isinstance(node, ast.Assert) and t(node.test):
            yield self.finding(
                module, node,
                f"assert on a traced value in jit-reachable `{qual}` — "
                f"use checkify or a host-side canary")
        elif isinstance(node, ast.Call):
            fn = dotted(node.func)
            if fn in self.CASTS and any(t(a) for a in node.args):
                yield self.finding(
                    module, node,
                    f"`{fn}()` concretizes a traced value in "
                    f"jit-reachable `{qual}`")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in self.PULL_METHODS
                  and t(node.func.value)):
                yield self.finding(
                    module, node,
                    f"`.{node.func.attr}()` pulls a traced value to "
                    f"host in jit-reachable `{qual}`")


# ------------------------------------------------------------- swar-guard

class SwarGuardRule(Rule):
    """Every call that turns the packed int16 path on (a truthy
    ``swar=`` / ``use_swar=`` argument) must be *dominated* by the
    overflow guard: the flag value must derive — through local
    assignments — from a ``swar_fits``-family call, or be a forwarded
    parameter of the enclosing function (checked at its callers). A
    bare ``swar=True`` (probes, tests-in-ops) needs a pragma stating
    why the geometry cannot overflow."""

    name = "swar-guard"
    blurb = ("packed-int16 entry points not dominated by a `swar_fits`-family overflow guard")
    FLAG_PARAMS = {"swar", "use_swar"}
    GUARDS = {"swar_fits", "_swar_choice", "swar_ok", "pallas_swar_ok"}

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/ops/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for fi in project.functions:
            if fi.module is not module:
                continue
            for call in iter_own_calls(fi.node):
                out.extend(self._check_call(project, module, fi, call))
        return out

    def _flag_args(self, project: Project, call: ast.Call):
        """(param_name, value_expr) for every packed-path flag this call
        passes — by keyword, or positionally via the resolved callee
        signature."""
        for kw in call.keywords:
            if kw.arg in self.FLAG_PARAMS:
                yield kw.arg, kw.value
        for callee in project.resolve(call):
            if not (set(callee.all_params()) & self.FLAG_PARAMS):
                continue
            mapped = map_call_args(call, callee)
            for p in self.FLAG_PARAMS:
                v = mapped.get(p)
                if v is not None and not any(kw.arg == p
                                             for kw in call.keywords):
                    yield p, v
            break

    def _check_call(self, project, module, fi, call):
        for pname, value in self._flag_args(project, call):
            if isinstance(value, ast.Constant):
                if not value.value:
                    continue  # literal off-switch
                yield self.finding(
                    module, call,
                    f"`{pname}={value.value!r}` enables the packed "
                    f"int16 path unguarded — derive it from "
                    f"swar_fits()/swar_ok() (or pragma with the "
                    f"geometry argument)")
            elif not self._guard_derived(project, fi, value):
                yield self.finding(
                    module, call,
                    f"`{pname}` value does not derive from a "
                    f"swar_fits()/swar_ok() guard on any assignment "
                    f"path — packed int16 scores can overflow "
                    f"silently")

    def _guard_derived(self, project: Project, fi, expr: ast.AST,
                       depth: int = 0) -> bool:
        """Does ``expr`` derive from a guard call through assignments in
        the lexical function chain (or forward a parameter)?"""
        if depth > 8:
            return False
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) \
                    and last_segment(dotted(node.func)) in self.GUARDS:
                return True
        names = {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}
        if not names:
            return False
        chain = [fi] + project.enclosing(fi)
        for name in names:
            if name in self.FLAG_PARAMS and any(
                    name in f.all_params() for f in chain):
                return True  # conventional pass-through: callers checked
            for f in chain:
                for node in iter_own_nodes(f.node):
                    value = None
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == name
                            for t in node.targets):
                        value = node.value
                    elif isinstance(node, ast.NamedExpr) and isinstance(
                            node.target, ast.Name) \
                            and node.target.id == name:
                        value = node.value
                    if value is not None and self._guard_derived(
                            project, f, value, depth + 1):
                        return True
        return False


# ---------------------------------------------------- swallowed-exception

class SwallowedExceptionRule(Rule):
    """``except Exception`` (or bare / BaseException) handlers must
    re-raise, log through the sanctioned sinks (``utils.logger.warn`` /
    ``log_swallowed`` / ``warnings.warn`` / a repo function that
    transitively does), or carry a pragma with the reason the fault is
    safe to swallow."""

    name = "swallowed-exception"
    blurb = ("broad `except` that neither re-raises nor logs")
    BROAD = {"Exception", "BaseException"}
    # calls that transfer control out of the handler like a raise does
    TERMINAL_CALLS = {"pytest.skip", "pytest.fail", "pytest.xfail",
                      "pytest.exit", "sys.exit", "os.abort"}

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if self._handled(project, node):
                continue
            out.append(self.finding(
                module, node,
                "broad `except` neither re-raises nor logs — route "
                "through utils.logger (log_swallowed/warn) or pragma "
                "with the reason"))
        return out

    def _is_broad(self, type_node) -> bool:
        if type_node is None:
            return True  # bare except
        names = ([dotted(type_node)] if not isinstance(type_node, ast.Tuple)
                 else [dotted(e) for e in type_node.elts])
        return any(last_segment(n) in self.BROAD for n in names if n)

    def _handled(self, project: Project, handler: ast.ExceptHandler) -> bool:
        # own nodes only: a raise/log inside a nested def the handler
        # merely *defines* (a callback that may never run) handles nothing
        for node in iter_own_nodes(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                if project.call_is_logging(node):
                    return True
                if dotted(node.func) in self.TERMINAL_CALLS:
                    return True
        return False


# ------------------------------------------------------ env-flag-registry

class EnvFlagRegistryRule(Rule):
    """All ``RACON_TPU_*`` environment reads go through
    ``racon_tpu/flags.py``; names read through the registry must be
    declared there. The registry itself is loaded (it is import-safe:
    stdlib only) so declarations are checked for real, not by regex."""

    name = "env-flag-registry"
    blurb = ("`RACON_TPU_*` env reads outside `racon_tpu/flags.py`, or of undeclared names")
    ENV_GETTERS = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
    REGISTRY_GETTERS = {"raw", "get_bool", "get_int", "get_float",
                        "get_str"}
    PREFIX = "RACON_TPU_"

    def __init__(self):
        self._registry: Optional[Set[str]] = None

    def _declared(self) -> Optional[Set[str]]:
        if self._registry is None:
            try:
                from racon_tpu.flags import REGISTRY
                self._registry = set(REGISTRY)
            # graftlint: disable=swallowed-exception (lint must run without the repo importable)
            except Exception:
                self._registry = set()  # unknown: skip declaration checks
        return self._registry

    def applies(self, rel: str) -> bool:
        return rel.endswith(".py") and rel != "racon_tpu/flags.py"

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                out.extend(self._check_call(module, node))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and dotted(node.value) in ("os.environ", "environ"):
                key = node.slice
                if isinstance(key, ast.Constant) \
                        and isinstance(key.value, str) \
                        and key.value.startswith(self.PREFIX):
                    out.append(self.finding(
                        module, node,
                        f"direct os.environ[{key.value!r}] read — go "
                        f"through racon_tpu.flags"))
        return out

    def _check_call(self, module, call):
        fn = dotted(call.func)
        arg0 = call.args[0] if call.args else None
        is_str = (isinstance(arg0, ast.Constant)
                  and isinstance(arg0.value, str))
        if fn in self.ENV_GETTERS and is_str \
                and arg0.value.startswith(self.PREFIX):
            yield self.finding(
                module, call,
                f"direct environment read of {arg0.value!r} — declare "
                f"it in racon_tpu/flags.py and use flags.get_*")
        elif last_segment(fn) in self.REGISTRY_GETTERS and is_str \
                and arg0.value.startswith(self.PREFIX):
            declared = self._declared()
            if declared and arg0.value not in declared:
                yield self.finding(
                    module, call,
                    f"flag {arg0.value!r} is not declared in "
                    f"racon_tpu/flags.py REGISTRY")


# ------------------------------------------------- host-sync-in-hot-loop

class HostSyncRule(Rule):
    """No device->host pulls inside per-chunk loops: a
    ``block_until_ready``/``jax.device_get``/``np.asarray``-of-a-device-
    value inside a ``for``/``while`` serializes the async dispatch
    pipeline once per iteration (the tunnel charges ~0.2-1s per sync).
    ``fetch_global``/``to_global`` are the sanctioned transfer
    primitives — their bodies are exempt, and values they return are
    host-side."""

    name = "host-sync-in-hot-loop"
    blurb = ("device->host pulls inside per-chunk loops")
    EXEMPT_FUNCS = {"fetch_global", "to_global"}
    # calls whose results live on device (host pulls of these are syncs)
    DEVICE_PRODUCERS = {"_dispatch", "align_chain", "sharded_align",
                        "sharded_refine_loop"}
    PULLERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
    CASTS = {"int", "float", "bool"}

    def applies(self, rel: str) -> bool:
        return rel.startswith("racon_tpu/") and rel.endswith(".py")

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        jit_names = {fi.name for fi in project.functions
                     if fi.is_jit_root}
        for fi in project.functions:
            if fi.module is not module or fi.name in self.EXEMPT_FUNCS:
                continue
            device = self._device_names(fi, jit_names)
            for loop in iter_own_nodes(fi.node):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    f = self._sync_finding(module, fi, node, device)
                    if f is not None:
                        out.append(f)
        return out

    def _device_names(self, fi, jit_names) -> Set[str]:
        """Names in ``fi`` assigned from device-producing calls (jitted
        repo kernels, the dispatch seams, jnp/lax ops)."""
        device: Set[str] = set()
        for node in iter_own_nodes(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if not isinstance(v, ast.Call):
                continue
            fn = dotted(v.func)
            seg = last_segment(fn)
            if seg in self.EXEMPT_FUNCS:
                continue  # sanctioned transfer: results are host-side
            if (seg in jit_names or seg in self.DEVICE_PRODUCERS
                    or (fn or "").startswith(("jnp.", "lax."))):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            device.add(n.id)
        return device

    def _sync_finding(self, module, fi, call, device):
        fn = dotted(call.func)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "block_until_ready":
            return self.finding(
                module, call,
                f"`.block_until_ready()` inside a loop in "
                f"`{fi.qualname}` serializes the dispatch pipeline "
                f"per iteration")
        if fn in ("jax.device_get", "jax.block_until_ready"):
            return self.finding(
                module, call,
                f"`{fn}` inside a loop in `{fi.qualname}` — fetch once "
                f"per chunk through fetch_global")
        tainted = lambda e: any(
            isinstance(n, ast.Name) and n.id in device
            for n in ast.walk(e))
        if fn in self.PULLERS and call.args and tainted(call.args[0]):
            return self.finding(
                module, call,
                f"`{fn}` of a device value inside a loop in "
                f"`{fi.qualname}` — a hidden device->host pull per "
                f"iteration")
        if fn in self.CASTS and call.args and tainted(call.args[0]):
            return self.finding(
                module, call,
                f"`{fn}()` of a device value inside a loop in "
                f"`{fi.qualname}` — a hidden sync per iteration")
        return None


# -------------------------------------------------------- span-discipline

class SpanDisciplineRule(Rule):
    """Observability spans only via ``with obs.span(...):`` — every
    ``obs.span(...)`` call must appear *directly* as a ``with`` item
    (``with obs.span(...):`` / ``with obs.span(...) as s:``, including
    multi-item withs).  Assigning a span to a name, calling
    ``__enter__``/``__exit__`` by hand, or passing a fresh span into a
    helper builds a manual begin/end pair that leaks the span open when
    an exception unwinds between the calls — the exact failure mode the
    context-manager protocol exists to close.  The tracer internals
    (``racon_tpu/obs/``) are exempt; a deliberate exception (e.g. an
    identity probe in a test) takes a reasoned pragma."""

    name = "span-discipline"
    blurb = ("`obs.span(...)` used any way other than directly as a `with` item")
    # dotted call names that create a span (obs.span is the repo idiom;
    # the bare name covers `from racon_tpu.obs import span`)
    SPAN_CALLS = {"obs.span", "span", "trace.span", "obs.trace.span"}

    def applies(self, rel: str) -> bool:
        return (rel.startswith("racon_tpu/") and rel.endswith(".py")
                and not rel.startswith("racon_tpu/obs/"))

    def check(self, project: Project, module: Module) -> List[Finding]:
        out: List[Finding] = []
        with_items: Set[int] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_items.add(id(item.context_expr))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted(node.func)
            if fn not in self.SPAN_CALLS or id(node) in with_items:
                continue
            out.append(self.finding(
                module, node,
                f"`{fn}(...)` used outside a `with` item — a span held "
                f"by hand leaks open when an exception unwinds; write "
                f"`with {fn}(...):` (or pragma with the reason)"))
        return out


# imported at the bottom so the concurrency and compile-surface packs
# can subclass Rule / build Findings without a circular import (both
# names are bound above by the time these lines run)
from .compilesurface import COMPILE_SURFACE_RULES  # noqa: E402
from .concurrency import CONCURRENCY_RULES  # noqa: E402
from .contracts import CONTRACT_RULES  # noqa: E402

ALL_RULES = [TracerLeakRule(), SwarGuardRule(), SwallowedExceptionRule(),
             EnvFlagRegistryRule(), HostSyncRule(), SpanDisciplineRule(),
             *CONCURRENCY_RULES, *COMPILE_SURFACE_RULES, *CONTRACT_RULES]
RULES_BY_NAME = {r.name: r for r in ALL_RULES}
