"""Fixture-based self-tests for every graftlint rule.

Each rule runs (unscoped) over a seeded-violation fixture and its clean
twin; the expected finding counts are exact, so a rule that goes blind
(0 findings on the bad fixture) or noisy (findings on the clean twin)
fails the lint shard before the repo-wide run. Run via
``python -m tools.analysis --selftest`` (CI) or tests/test_graftlint.py.
"""

from __future__ import annotations

import pathlib
import sys

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

# fixture -> (rule name, expected reported, expected pragma-suppressed)
EXPECT = {
    "tracer_leak_bad.py": ("tracer-leak", 5, 0),
    "tracer_leak_ok.py": ("tracer-leak", 0, 0),
    "swar_guard_bad.py": ("swar-guard", 3, 0),
    "swar_guard_ok.py": ("swar-guard", 0, 1),
    "swallowed_bad.py": ("swallowed-exception", 4, 0),
    "swallowed_ok.py": ("swallowed-exception", 0, 1),
    "env_flag_bad.py": ("env-flag-registry", 3, 0),
    "env_flag_ok.py": ("env-flag-registry", 0, 0),
    "host_sync_bad.py": ("host-sync-in-hot-loop", 4, 0),
    "host_sync_ok.py": ("host-sync-in-hot-loop", 0, 0),
    "span_discipline_bad.py": ("span-discipline", 3, 0),
    "span_discipline_ok.py": ("span-discipline", 0, 1),
    # the concurrency & durability pack (round 15)
    "lock_discipline_bad.py": ("lock-discipline", 2, 0),
    "lock_discipline_ok.py": ("lock-discipline", 0, 1),
    "blocking_under_lock_bad.py": ("blocking-under-lock", 3, 0),
    "blocking_under_lock_ok.py": ("blocking-under-lock", 0, 1),
    # round 16 grew both: the fsync'd-append (journal) allowlist and a
    # raw spool-write violation; the depth-1 supervisor wiring and a
    # non-polling helper that must still report
    "atomic_write_bad.py": ("atomic-write-discipline", 3, 0),
    "atomic_write_ok.py": ("atomic-write-discipline", 0, 1),
    "thread_lifecycle_bad.py": ("thread-lifecycle", 3, 0),
    "thread_lifecycle_ok.py": ("thread-lifecycle", 0, 1),
    "scope_discipline_bad.py": ("scope-discipline", 3, 0),
    "scope_discipline_ok.py": ("scope-discipline", 0, 1),
    # the compile-surface pack (round 18)
    "jit_shape_bad.py": ("jit-shape-hazard", 3, 0),
    "jit_shape_ok.py": ("jit-shape-hazard", 0, 1),
    "dtype_drift_bad.py": ("dtype-drift", 3, 0),
    "dtype_drift_ok.py": ("dtype-drift", 0, 1),
    "jit_in_loop_bad.py": ("jit-in-loop", 3, 0),
    "jit_in_loop_ok.py": ("jit-in-loop", 0, 1),
    "warmup_coverage_bad.py": ("warmup-coverage", 3, 0),
    "warmup_coverage_ok.py": ("warmup-coverage", 0, 1),
    "host_transfer_bad.py": ("host-transfer-in-jit", 3, 0),
    "host_transfer_ok.py": ("host-transfer-in-jit", 0, 1),
    # round 19: the fused resident align->consensus dataflow shape —
    # mid-derive numpy round-trips on the jit'd row-derive/lane-gather
    # roots are exactly the transfers the resident path eliminates
    "resident_dataflow_bad.py": ("host-transfer-in-jit", 3, 0),
    "resident_dataflow_ok.py": ("host-transfer-in-jit", 0, 1),
    # round 20: the first-party overlapper shape — seed/chain arena
    # geometry statics fed raw runtime counts vs the shared-quantizer
    # discipline overlap_seed.py/chain.py actually use
    "overlap_chain_bad.py": ("jit-shape-hazard", 3, 0),
    "overlap_chain_ok.py": ("warmup-coverage", 0, 1),
    # round 21: the device seed-join shape — np.* on traced join
    # intermediates inside the jit'd sort/expand kernels (the transfers
    # the device join eliminates) vs the double-buffered chain-chunk
    # pipeline fetching only through the sanctioned primitive
    "overlap_join_bad.py": ("host-transfer-in-jit", 3, 0),
    "overlap_join_ok.py": ("host-sync-in-hot-loop", 0, 1),
    # the contract pack (round 22): string-keyed registries and
    # lifecycle machines checked against racon_tpu/contracts.py
    "metric_registry_bad.py": ("metric-registry", 3, 0),
    "metric_registry_ok.py": ("metric-registry", 0, 1),
    "span_registry_bad.py": ("span-registry", 3, 0),
    "span_registry_ok.py": ("span-registry", 0, 1),
    "fault_site_bad.py": ("fault-site-registry", 3, 0),
    "fault_site_ok.py": ("fault-site-registry", 0, 1),
    "schema_coherence_bad.py": ("schema-coherence", 3, 0),
    "schema_coherence_ok.py": ("schema-coherence", 0, 1),
    "state_transition_bad.py": ("state-transition", 3, 0),
    "state_transition_ok.py": ("state-transition", 0, 1),
    # pragma hygiene is driver-level: unknown rule names are findings
    "pragma_bad.py": ("pragma", 1, 0),
}


def run_selftest(verbose: bool = True) -> int:
    from . import run
    from .rules import RULES_BY_NAME

    failures = []
    for fixture, (rule_name, want, want_sup) in sorted(EXPECT.items()):
        path = FIXTURES / fixture
        rules = ([RULES_BY_NAME[rule_name]]
                 if rule_name in RULES_BY_NAME else [])
        reported, suppressed = run([str(path)], rules=rules, scoped=False)
        reported = [f for f in reported if f.rule == rule_name]
        if len(reported) != want or len(suppressed) != want_sup:
            failures.append(
                f"{fixture}: rule {rule_name} reported "
                f"{len(reported)} (want {want}), suppressed "
                f"{len(suppressed)} (want {want_sup}):\n"
                + "\n".join(f"    {f}" for f in reported))
        elif verbose:
            print(f"selftest ok: {fixture} [{rule_name}] "
                  f"{want} reported / {want_sup} suppressed")
    if failures:
        print("graftlint selftest FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"graftlint selftest: {len(EXPECT)} fixtures ok")
    return 0
