#!/usr/bin/env python
"""Micro-bench of vote scatter-add formulations on the real chip."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit_pipelined(dispatch, k=10, n=2):
    jax.block_until_ready(dispatch())
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = dispatch()
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / k)
    return best


B, S, nW, VOT = 2048, 1280, 128, 30720
rng = np.random.default_rng(0)
idx = jnp.asarray(rng.integers(0, VOT + 1, (B, S)), jnp.int32)
w8 = jnp.asarray(rng.integers(0, 94, (B, S)), jnp.uint8)
ok = jnp.asarray(rng.random(B) < 0.9)
win_of = jnp.asarray(rng.integers(0, nW, B), jnp.int32)


@jax.jit
def cur(idx, w8, ok, win_of):
    wsv = w8.astype(jnp.float32) * ok[:, None].astype(jnp.float32)
    flat = (win_of[:, None] * (VOT + 1) + idx).reshape(-1)
    weighted = jnp.zeros(nW * (VOT + 1), jnp.float32).at[flat].add(
        wsv.reshape(-1))
    unweighted = jnp.zeros(nW * (VOT + 1), jnp.int32).at[flat].add(
        (wsv.reshape(-1) > 0).astype(jnp.int32))
    return weighted, unweighted


@jax.jit
def i32both(idx, w8, ok, win_of):
    wsv = w8.astype(jnp.int32) * ok[:, None].astype(jnp.int32)
    flat = (win_of[:, None] * (VOT + 1) + idx).reshape(-1)
    weighted = jnp.zeros(nW * (VOT + 1), jnp.int32).at[flat].add(
        wsv.reshape(-1))
    unweighted = jnp.zeros(nW * (VOT + 1), jnp.int32).at[flat].add(
        (wsv.reshape(-1) > 0).astype(jnp.int32))
    return weighted, unweighted


@jax.jit
def vec2(idx, w8, ok, win_of):
    wsv = w8.astype(jnp.int32) * ok[:, None].astype(jnp.int32)
    flat = (win_of[:, None] * (VOT + 1) + idx).reshape(-1)
    upd = jnp.stack([wsv.reshape(-1), (wsv.reshape(-1) > 0
                                       ).astype(jnp.int32)], axis=-1)
    out = jnp.zeros((nW * (VOT + 1), 2), jnp.int32).at[flat].add(upd)
    return out[:, 0], out[:, 1]


@jax.jit
def packed_u32(idx, w8, ok, win_of):
    wsv = w8.astype(jnp.uint32) * ok[:, None].astype(jnp.uint32)
    flat = (win_of[:, None] * (VOT + 1) + idx).reshape(-1)
    comb = (wsv + ((wsv > 0).astype(jnp.uint32) << 23)).reshape(-1)
    out = jnp.zeros(nW * (VOT + 1), jnp.uint32).at[flat].add(comb)
    return (out & ((1 << 23) - 1)), (out >> 23)


for name, fn in [("cur f32+i32", cur), ("i32 both", i32both),
                 ("vec2 single", vec2), ("packed u32", packed_u32)]:
    t = timeit_pipelined(lambda fn=fn: fn(idx, w8, ok, win_of))
    print(f"{name:14s} {t * 1e3:8.2f} ms", flush=True)

# realistic distribution: per-row ascending col votes, ~20% to the shared
# per-window sink (padding steps) — collisions serialize scatter lanes
idx_r = np.minimum(np.maximum(
    (np.arange(S)[None, :] // 8 * 8 // 10) * 8
    + rng.integers(0, 6, (B, S)), 0), VOT - 1).astype(np.int32)
sink_mask = rng.random((B, S)) < 0.2
idx_r[sink_mask] = VOT
idx_r = jnp.asarray(idx_r)
for name, fn in [("cur/realsink", cur), ("packed/realsink", packed_u32)]:
    t = timeit_pipelined(lambda fn=fn: fn(idx_r, w8, ok, win_of))
    print(f"{name:16s} {t * 1e3:8.2f} ms", flush=True)
