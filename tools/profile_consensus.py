#!/usr/bin/env python
"""Stage-level timing of the device consensus round on the real chip.

Decomposes one ``refine_round`` into its stages and times each with
``block_until_ready`` (best of N), so perf work attacks measured hot spots
instead of guesses. Also times the whole round and the full engine run for
cross-checking, and sweeps the Pallas pair-block caps when asked.

Usage:
    python tools/profile_consensus.py [--scale MBP] [--fwd-p N] [--walk-p N]
                                      [--rounds N] [--xla]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA = "/root/reference/test/data"


def timeit_pipelined(dispatch, k=10, n=2):
    """Device time per call: dispatch ``k`` back-to-back (async), block
    once, divide — the host<->device sync latency (~130 ms on the tunnel)
    amortizes away, leaving the true per-call device time."""
    import jax
    jax.block_until_ready(dispatch())  # compile / warm
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = dispatch()
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / k)
    return best


def build_lambda_windows():
    from racon_tpu.core.polisher import create_polisher
    p = create_polisher(
        f"{DATA}/sample_reads.fastq.gz", f"{DATA}/sample_overlaps.sam.gz",
        f"{DATA}/sample_layout.fasta.gz", num_threads=8)
    p.initialize()
    return p.windows


def build_scale_windows(mbp):
    import numpy as np
    from racon_tpu.core.window import Window, WindowType
    rng = np.random.default_rng(17)
    bases = np.frombuffer(b"ACGT", dtype=np.uint8)
    n_windows = int(mbp * 1e6) // 500
    windows = []
    for wi in range(n_windows):
        truth = bases[rng.integers(0, 4, 500)]
        bb = truth.copy()
        flips = rng.random(500) < 0.10
        bb[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
        win = Window(0, wi, WindowType.TGS, bb.tobytes(), b"!" * 500)
        for _ in range(30):
            layer = truth.copy()
            flips = rng.random(500) < 0.08
            layer[flips] = bases[rng.integers(0, 4, int(flips.sum()))]
            layer = np.delete(layer, rng.integers(0, len(layer), 12))
            ins_at = rng.integers(0, len(layer), 12)
            layer = np.insert(layer, ins_at,
                              bases[rng.integers(0, 4, 12)])
            win.add_layer(layer.tobytes(), b"9" * len(layer), 0, 499)
        windows.append(win)
    return windows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.0)
    ap.add_argument("--fwd-p", type=int, default=0)
    ap.add_argument("--walk-p", type=int, default=0)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--xla", action="store_true")
    args = ap.parse_args()

    from racon_tpu.ops import pallas_nw
    if args.fwd_p:
        pallas_nw.FWD_P_CAP = args.fwd_p
    if args.walk_p:
        pallas_nw.WALK_P_CAP = args.walk_p

    import jax
    import jax.numpy as jnp
    import numpy as np
    from racon_tpu.ops import poa as poa_mod
    from racon_tpu.ops.poa import (
        GROW, K_INS, CH, DEL, Q_PAD, T_PAD, TpuPoaConsensus, _Work,
        _consensus_kernel, _accumulate_votes, _vote_from_ops, refine_round)
    from racon_tpu.core.backends import CpuPoaConsensus

    print(f"devices: {jax.devices()}  fwd_p={pallas_nw.FWD_P_CAP} "
          f"walk_p={pallas_nw.WALK_P_CAP}", flush=True)

    windows = (build_scale_windows(args.scale) if args.scale
               else build_lambda_windows())
    print(f"{len(windows)} windows", flush=True)

    eng = TpuPoaConsensus(3, -5, -4, fallback=CpuPoaConsensus(3, -5, -4, 8),
                          rounds=args.rounds)

    # replicate run()'s sizing
    works = [(i, _Work(w, eng.max_depth, eng.stats))
             for i, w in enumerate(windows) if len(w.sequences) >= 3]
    live = [(i, w) for i, w in works if len(w.layers) >= 2]
    max_bb = max(len(w.backbone) for _, w in live)
    L = max(256, -(-max_bb // 256) * 256)
    Lq = L + eng.band
    Lb = min(L + GROW, Lq)
    live = [(i, w) for i, w in live
            if all(len(s) <= Lq for s, _, _, _ in w.layers)
            and len(w.backbone) <= Lb]
    max_nm = max(len(s) + min((e - b + 1) + 64, Lb)
                 for _, w in live for s, _, b, e in w.layers)
    steps = -(-min(-(-max_nm // 128) * 128, 2 * Lq) // 128) * 128
    # one group only (profile a single launch)
    from racon_tpu.ops.poa import MAX_GROUP_PAIRS
    total_pairs = sum(len(w.layers) for _, w in live)
    if total_pairs > MAX_GROUP_PAIRS:
        acc = []
        s = 0
        for i, w in live:
            if s + len(w.layers) > MAX_GROUP_PAIRS:
                break
            acc.append((i, w))
            s += len(w.layers)
        live = acc
        total_pairs = s
    launch = eng._launch_group(live, Lq, Lb)
    n_, qcodes, qweights, win_of, real = launch["static"]
    (bg, ed, bcodes, bweights, blen, covs, ever, frozen, conv,
     dropped) = launch["state"]
    nWp = launch["nWp"]
    B = qcodes.shape[0]
    print(f"pairs={total_pairs} B={B} Lq={Lq} Lb={Lb} steps={steps} "
          f"nWp={nWp} band={eng.band}", flush=True)

    use_pallas = (not args.xla) and pallas_nw.pallas_ok()
    print(f"use_pallas={use_pallas}", flush=True)

    band = eng.band
    c = band // 2
    width = c + Lq + band
    m_ = ed - bg + 1

    @jax.jit
    def build_rows(n, qcodes, bg, ed, bcodes):
        m = ed - bg + 1
        core = jnp.where((Lq - 1 - jnp.arange(Lq, dtype=jnp.int32))[None, :]
                         < n[:, None],
                         jnp.flip(qcodes, axis=1), jnp.uint8(Q_PAD))
        qrp = jnp.concatenate(
            [jnp.full((B, c), Q_PAD, jnp.uint8), core,
             jnp.full((B, band), Q_PAD, jnp.uint8)], axis=1)
        cols = jnp.arange(width, dtype=jnp.int32)[None, :] - c
        bbrow = jnp.take(bcodes, win_of, axis=0)
        y = jnp.pad(bbrow, ((0, 0), (c, width - c - Lb)))
        for k in range((Lb - 1).bit_length()):
            y = jnp.where(((bg[:, None] >> k) & 1).astype(bool),
                          jnp.roll(y, -(1 << k), axis=1), y)
        tp = jnp.where((cols >= 0) & (cols < m[:, None]), y,
                       jnp.uint8(T_PAD))
        return qrp, tp

    qrp, tp = jax.block_until_ready(build_rows(n_, qcodes, bg, ed, bcodes))
    t_rows = timeit_pipelined(lambda: build_rows(n_, qcodes, bg, ed, bcodes))
    print(f"rows:      {t_rows * 1e3:8.2f} ms", flush=True)

    if use_pallas:
        from racon_tpu.ops.pallas_nw import pallas_nw_fwd, pallas_walk_vote
        fwd = lambda: pallas_nw_fwd(qrp, tp, n_, m_, max_len=Lq, band=band,
                                    steps=steps)
        packed, score = jax.block_until_ready(fwd())
        t_fwd = timeit_pipelined(fwd)
        print(f"fwd:       {t_fwd * 1e3:8.2f} ms", flush=True)

        wv = lambda: pallas_walk_vote(packed, n_, m_, bg, qcodes, qweights,
                                      band=band, L=Lb, K=K_INS, CH=CH,
                                      DEL=DEL)
        idx, w8, fi, fj = jax.block_until_ready(wv())
        t_walk = timeit_pipelined(wv)
        print(f"walk+vote: {t_walk * 1e3:8.2f} ms", flush=True)

        okp = (fi == 0) & (fj == 0) & (score < (band // 2))
        sc = jax.jit(lambda idx, w8, okp, win_of: _accumulate_votes(
            idx, w8.astype(jnp.int32), okp, win_of, m_, bg, n_, score,
            n_windows=nWp, L=Lb, K=K_INS, band=band))
        t_scatter = timeit_pipelined(lambda: sc(idx, w8, okp, win_of))
        print(f"accum:     {t_scatter * 1e3:8.2f} ms", flush=True)
        weighted, unweighted, _, _ = sc(idx, w8, okp, win_of)
    else:
        from racon_tpu.ops.nw import _nw_wavefront_kernel, _walk_ops_kernel
        fwd = lambda: _nw_wavefront_kernel(qrp, tp, n_, m_, max_len=Lq,
                                           band=band, steps=steps)
        packed, score = jax.block_until_ready(fwd())
        t_fwd = timeit_pipelined(fwd)
        print(f"fwd:       {t_fwd * 1e3:8.2f} ms", flush=True)
        wk = lambda: _walk_ops_kernel(packed, n_, m_, band=band)
        ops, fi, fj = jax.block_until_ready(wk())
        t_walk = timeit_pipelined(wk)
        print(f"walk:      {t_walk * 1e3:8.2f} ms", flush=True)
        def vt():
            idx, wv, okp = _vote_from_ops(
                ops, fi, fj, score, n_, m_, qcodes, qweights, bg,
                max_len=Lq, band=band, L=Lb, K=K_INS)
            w_, u_, _, _ = _accumulate_votes(idx, wv, okp, win_of, m_, bg,
                                             n_, score, n_windows=nWp,
                                             L=Lb, K=K_INS, band=band)
            return w_, u_, okp
        weighted, unweighted, okp = jax.block_until_ready(vt())
        t_scatter = timeit_pipelined(vt)
        print(f"vote+accum:{t_scatter * 1e3:8.2f} ms", flush=True)

    ck = jax.jit(lambda w, u: _consensus_kernel(
        w, u, bcodes, bweights, blen,
        jnp.float32(eng.ins_theta), jnp.float32(eng.del_beta),
        L=Lb, K=K_INS))
    t_cons = timeit_pipelined(lambda: ck(weighted, unweighted))
    print(f"consensus: {t_cons * 1e3:8.2f} ms", flush=True)

    rr = lambda: refine_round(
        n_, qcodes, qweights, win_of, real, bg, ed, bcodes, bweights,
        blen, covs, ever, frozen, conv, dropped,
        jnp.float32(eng.ins_theta), jnp.float32(eng.del_beta),
        n_windows=nWp, max_len=Lq, band=band, Lb=Lb, K=K_INS,
        steps=steps, use_pallas=use_pallas)
    t_round = timeit_pipelined(rr)
    print(f"round:     {t_round * 1e3:8.2f} ms "
          f"(stages sum {1e3 * (t_rows + t_fwd + t_walk + t_scatter + t_cons):.2f})",
          flush=True)

    # whole-engine wall for cross-check
    t0 = time.perf_counter()
    eng.run(windows, trim=True)
    print(f"engine cold: {time.perf_counter() - t0:.2f}s", flush=True)
    t0 = time.perf_counter()
    eng.run(windows, trim=True)
    print(f"engine warm: {time.perf_counter() - t0:.2f}s  stats={eng.stats}",
          flush=True)


if __name__ == "__main__":
    main()
