#!/usr/bin/env python
"""Record device-engine goldens for every scenario the reference records
CUDA goldens for (test/racon_test.cpp:292-496): eight consensus runs
(incl. unit/e2e score sets and banded) + four fragment-correction runs, all through the accelerated engines
(consensus_backend="tpu"; -f also aligner_backend="tpu"). Prints one line
per scenario; values are bit-reproducible across the CPU-mesh XLA kernels
and the on-chip Pallas kernels, so tests assert them exactly.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA = "/root/reference/test/data"


def rc_distance(polished):
    from racon_tpu.io import parse_fasta
    from racon_tpu import native
    ref = list(parse_fasta(f"{DATA}/sample_reference.fasta.gz"))[0]
    return native.edit_distance(polished.reverse_complement, ref.data)


def consensus(reads, overlaps, tag, **kw):
    from racon_tpu.core.polisher import create_polisher
    t0 = time.perf_counter()
    p = create_polisher(f"{DATA}/{reads}", f"{DATA}/{overlaps}",
                        f"{DATA}/sample_layout.fasta.gz", num_threads=8,
                        consensus_backend="tpu", **kw)
    p.initialize()
    (polished,) = p.polish(True)
    d = rc_distance(polished)
    stats = p.consensus.stats
    print(f"{tag}: rc={d} device_windows={stats['device_windows']} "
          f"fallback={stats['fallback_windows']} "
          f"({time.perf_counter() - t0:.0f}s)", flush=True)


def fragment(reads, overlaps, tag):
    from racon_tpu.core.polisher import PolisherType, create_polisher
    t0 = time.perf_counter()
    p = create_polisher(f"{DATA}/{reads}", f"{DATA}/{overlaps}",
                        f"{DATA}/{reads}", PolisherType.F,
                        window_length=500, quality_threshold=10.0,
                        error_threshold=0.3, match=1, mismatch=-1, gap=-1,
                        num_threads=8, consensus_backend="tpu",
                        aligner_backend="tpu")
    p.initialize()
    out = p.polish(False)
    total = sum(len(s.data) for s in out)
    stats = p.consensus.stats
    print(f"{tag}: n={len(out)} total={total} "
          f"device_windows={stats['device_windows']} "
          f"fallback={stats['fallback_windows']} "
          f"({time.perf_counter() - t0:.0f}s)", flush=True)


def fragment_kc(tag):
    from racon_tpu.core.polisher import PolisherType, create_polisher
    t0 = time.perf_counter()
    p = create_polisher(f"{DATA}/sample_reads.fastq.gz",
                        f"{DATA}/sample_ava_overlaps.paf.gz",
                        f"{DATA}/sample_reads.fastq.gz", PolisherType.C,
                        window_length=500, quality_threshold=10.0,
                        error_threshold=0.3, match=1, mismatch=-1, gap=-1,
                        num_threads=8, consensus_backend="tpu",
                        aligner_backend="tpu")
    p.initialize()
    out = p.polish(True)
    total = sum(len(s.data) for s in out)
    print(f"{tag}: n={len(out)} total={total} "
          f"device_windows={p.consensus.stats['device_windows']} "
          f"({time.perf_counter() - t0:.0f}s)", flush=True)


def main():
    import jax
    print(f"devices: {jax.devices()}", flush=True)
    consensus("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
              "consensus_fastq_paf")
    consensus("sample_reads.fasta.gz", "sample_overlaps.paf.gz",
              "consensus_fasta_paf")
    consensus("sample_reads.fastq.gz", "sample_overlaps.sam.gz",
              "consensus_fastq_sam")
    consensus("sample_reads.fasta.gz", "sample_overlaps.sam.gz",
              "consensus_fasta_sam")
    consensus("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
              "consensus_w1000", window_length=1000)
    consensus("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
              "consensus_unit_scores", match=1, mismatch=-1, gap=-1)
    consensus("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
              "consensus_e2e_scores", match=8, mismatch=-6, gap=-8)
    consensus("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
              "consensus_banded", banded=True)
    fragment_kc("fragment_kc_ava")
    fragment("sample_reads.fastq.gz", "sample_ava_overlaps.paf.gz",
             "fragment_kf_paf_q")
    fragment("sample_reads.fasta.gz", "sample_ava_overlaps.paf.gz",
             "fragment_kf_paf_noq")
    fragment("sample_reads.fastq.gz", "sample_ava_overlaps.mhap.gz",
             "fragment_kf_mhap")


if __name__ == "__main__":
    main()
