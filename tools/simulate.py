"""Vectorized ONT-like read/assembly simulator for the pipeline bench.

Generates, for a random truth genome: a draft assembly (the polishing
target, mutated from truth like a raw-read-consensus layout), a read set
at a given coverage with independent errors, and the true PAF overlap of
every read against the draft — the full input triple the reference's CI
golden pipeline consumes (reads + overlaps + contigs,
``/root/reference/ci/gpu/cuda_test.sh:29-42``), at arbitrary scale.

Error injection is fully vectorized (np.repeat over per-base copy counts
for indels + one flat substitution mask), so generating a 300 Mbp read
set takes seconds, not the minutes a per-read loop costs. Coordinates of
each read's span are mapped through the draft's indel profile
(cumulative copy-count sums), so PAF target coordinates are exact in
draft space.
"""

from __future__ import annotations

import numpy as np

BASES = np.frombuffer(b"ACGT", dtype=np.uint8)


def _mutate(seq, rng, del_p, ins_p, sub_p):
    """Apply indels via copy counts + substitutions; returns (mutated,
    copy_counts) where ``counts[i]`` is how many output bases truth base
    ``i`` produced (0 = deleted, 2 = insertion after)."""
    r = rng.random(len(seq))
    counts = np.ones(len(seq), np.int64)
    counts[r < del_p] = 0
    counts[(r >= del_p) & (r < del_p + ins_p)] = 2
    out = np.repeat(seq, counts)
    sub = rng.random(len(out)) < sub_p
    out[sub] = BASES[rng.integers(0, 4, int(sub.sum()))]
    return out, counts


_COMP = np.zeros(256, np.uint8)
_COMP[ord("A")] = ord("T")
_COMP[ord("T")] = ord("A")
_COMP[ord("C")] = ord("G")
_COMP[ord("G")] = ord("C")


def _revcomp(arr):
    return _COMP[arr[::-1]]


def simulate(mbp: float, seed: int = 23, coverage: int = 30,
             mean_read: int = 7000, max_read: int = 8000,
             min_read: int = 2000, n_contigs: int = 0):
    """Returns (reads_fastq_bytes, paf_bytes, contigs_fasta_bytes,
    truths) for a ``mbp``-megabase genome. ``truths`` is the list of
    truth contig byte strings (for post-polish quality checks)."""
    rng = np.random.default_rng(seed)
    total = int(mbp * 1e6)
    if not n_contigs:
        n_contigs = max(1, total // 2_000_000)
    sizes = [total // n_contigs] * n_contigs
    sizes[-1] += total - sum(sizes)

    fastq_parts = []
    paf_lines = []
    fasta_parts = []
    truths = []
    read_id = 0
    for ci, size in enumerate(sizes):
        truth = BASES[rng.integers(0, 4, size)]
        truths.append(truth.tobytes())
        tname = f"contig_{ci}".encode()

        # draft assembly: raw-read-layout error profile (~10%)
        draft, counts = _mutate(truth, rng, 0.02, 0.02, 0.06)
        # truth position -> draft position (exclusive prefix sum)
        t2d = np.concatenate(([0], np.cumsum(counts)))
        fasta_parts.append(b">" + tname + b"\n" + draft.tobytes() + b"\n")

        # reads: sample spans over truth, then inject independent errors
        n_reads = max(1, int(size * coverage) // mean_read)
        lens = np.clip(rng.normal(mean_read, 1500, n_reads).astype(np.int64),
                       min_read, min(max_read, size))
        starts = rng.integers(0, np.maximum(1, size - lens))
        order = np.argsort(starts)  # deterministic, irrelevant to output
        lens, starts = lens[order], starts[order]
        seg_bounds = np.concatenate(([0], np.cumsum(lens)))
        cat = np.empty(seg_bounds[-1], np.uint8)
        for k in range(n_reads):
            cat[seg_bounds[k]:seg_bounds[k + 1]] = \
                truth[starts[k]:starts[k] + lens[k]]
        mut, mcounts = _mutate(cat, rng, 0.03, 0.03, 0.06)
        out_lens = np.add.reduceat(mcounts, seg_bounds[:-1])
        out_bounds = np.concatenate(([0], np.cumsum(out_lens)))
        strands = rng.random(n_reads) < 0.5

        dlen = len(draft)
        for k in range(n_reads):
            rb = mut[out_bounds[k]:out_bounds[k + 1]]
            if strands[k]:
                rb = _revcomp(rb)
            name = f"read_{read_id}".encode()
            read_id += 1
            qual = b"9" * len(rb)
            fastq_parts.append(b"@" + name + b"\n" + rb.tobytes()
                               + b"\n+\n" + qual + b"\n")
            tb = int(t2d[starts[k]])
            te = int(t2d[starts[k] + lens[k]])
            te = max(te, tb + 1)
            paf_lines.append(b"\t".join([
                name, str(len(rb)).encode(), b"0", str(len(rb)).encode(),
                b"-" if strands[k] else b"+",
                tname, str(dlen).encode(), str(tb).encode(),
                str(min(te, dlen)).encode(),
                str(min(len(rb), te - tb)).encode(),
                str(max(len(rb), te - tb)).encode(), b"255"]) + b"\n")

    return (b"".join(fastq_parts), b"".join(paf_lines),
            b"".join(fasta_parts), truths)


def write_inputs(mbp: float, out_dir: str, seed: int = 23,
                 coverage: int = 30) -> dict:
    """Generate and write the input triple (+ truth contigs) to
    ``out_dir``. Exists as a CLI so benches can generate big workloads in
    a THROWAWAY subprocess: a 100 Mbp set materializes several GB of read
    bytes, and generating in-process would bake that into the parent's
    peak RSS — exactly the number the shard-runner bench budgets."""
    import os

    reads, paf, contigs, truths = simulate(mbp, seed=seed,
                                           coverage=coverage)
    os.makedirs(out_dir, exist_ok=True)
    paths = {"reads": os.path.join(out_dir, "reads.fastq"),
             "overlaps": os.path.join(out_dir, "ovl.paf"),
             "draft": os.path.join(out_dir, "draft.fasta"),
             "truth": os.path.join(out_dir, "truth.fasta")}
    truth_fa = b"".join(b">contig_%d\n%s\n" % (i, t)
                        for i, t in enumerate(truths))
    for key, blob in (("reads", reads), ("overlaps", paf),
                      ("draft", contigs), ("truth", truth_fa)):
        with open(paths[key], "wb") as f:
            f.write(blob)
    return paths


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(
        description="write a simulated assembly input triple "
                    "(reads.fastq, ovl.paf, draft.fasta, truth.fasta)")
    ap.add_argument("mbp", type=float)
    ap.add_argument("out_dir")
    ap.add_argument("--seed", type=int, default=23)
    ap.add_argument("--coverage", type=int, default=30)
    a = ap.parse_args()
    write_inputs(a.mbp, a.out_dir, seed=a.seed, coverage=a.coverage)
