#!/usr/bin/env python
"""Real-TPU quality + speed spot check for the device consensus engine.

Runs the λ-phage FASTQ+PAF pipeline with the TPU consensus backend on the
real chip and prints the rc edit distance vs NC_001416 (recorded device
golden: 1346; CPU golden: 1324) plus warm timing. Used between perf-work
stages to prove the device path's output is unchanged.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA = "/root/reference/test/data"


def main():
    from racon_tpu.core.polisher import create_polisher
    from racon_tpu.io import parse_fasta
    from racon_tpu import native

    t0 = time.perf_counter()
    p = create_polisher(f"{DATA}/sample_reads.fastq.gz",
                        f"{DATA}/sample_overlaps.paf.gz",
                        f"{DATA}/sample_layout.fasta.gz",
                        num_threads=8, consensus_backend="tpu")
    p.initialize()
    (polished,) = p.polish(True)
    wall = time.perf_counter() - t0
    ref = list(parse_fasta(f"{DATA}/sample_reference.fasta.gz"))[0]
    d = native.edit_distance(polished.reverse_complement, ref.data)
    print(f"rc_distance={d} (golden 1346)  stats={p.consensus.stats}  "
          f"wall={wall:.2f}s", flush=True)
    return 0 if d == 1346 else 1


if __name__ == "__main__":
    sys.exit(main())
